"""explain/ — batched device TreeSHAP + explanation serving.

Three layers of pinning:

1. the host oracle (``core/shap.py``) against brute-force Shapley values
   computed from the path-dependent conditional expectation (the
   reference's semantics, tree.cpp:609-716) — categorical-bitset splits,
   NaN/default-left routing and single-leaf stumps included;
2. the device kernel (``explain/kernel.py``) against that oracle to 1e-5
   on dense, NaN, categorical, multiclass and file-loaded fixtures, plus
   the SHAP local-accuracy identity (contributions sum to the raw
   score);
3. the serving surface: ``PredictorSession.explain``/``submit_explain``
   and ``POST /explain`` under a concurrent mixed ``/predict`` load,
   with the explain bucket family's compile count bounded by
   ceil(log2(explain_max_batch)) + 1.
"""
import itertools
import json
import math
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.core.shap import _expected_value, predict_contrib
from lightgbm_tpu.serve import PredictorSession, PredictServer


def _nan_matrix(rng, n, f_num, f_cat=0, cat_lo=-1, cat_hi=15):
    X = rng.normal(size=(n, f_num))
    X[rng.random((n, f_num)) < 0.08] = np.nan
    if f_cat:
        X = np.hstack([X, rng.integers(cat_lo, cat_hi, size=(n, f_cat)
                                       ).astype(np.float64)])
    return X


# ---------------------------------------------------------------------------
# brute-force Shapley reference (exponential, tiny trees only)
# ---------------------------------------------------------------------------

def _cond_exp(tree, x, S, node=0):
    """Path-dependent conditional expectation: features in S follow x's
    decision, the rest average children by training data counts —
    exactly the expectation TreeSHAP decomposes."""
    if tree.num_leaves == 1:
        return float(tree.leaf_value[0])
    if node < 0:
        return float(tree.leaf_value[~node])
    f = int(tree.split_feature[node])
    lc, rc = int(tree.left_child[node]), int(tree.right_child[node])
    if f in S:
        gl = bool(tree._decide(np.asarray([x[f]]), np.asarray([node]))[0])
        return _cond_exp(tree, x, S, lc if gl else rc)

    def cnt(n):
        return float(tree.leaf_count[~n] if n < 0
                     else tree.internal_count[n])
    return (cnt(lc) * _cond_exp(tree, x, S, lc)
            + cnt(rc) * _cond_exp(tree, x, S, rc)) / cnt(node)


def _brute_shap(tree, x, F):
    used = sorted({int(tree.split_feature[i])
                   for i in range(max(tree.num_leaves - 1, 0))})
    phi = np.zeros(F + 1)
    phi[F] = _expected_value(tree)
    U = len(used)
    for i in used:
        others = [f for f in used if f != i]
        for r in range(len(others) + 1):
            for S in itertools.combinations(others, r):
                w = (math.factorial(len(S))
                     * math.factorial(U - len(S) - 1) / math.factorial(U))
                phi[i] += w * (_cond_exp(tree, x, set(S) | {i})
                               - _cond_exp(tree, x, set(S)))
    return phi


def _brute_contrib(gbdt, X):
    F = X.shape[1]
    K = gbdt.num_tpi
    out = np.zeros((X.shape[0], K, F + 1))
    for i, t in enumerate(gbdt.models):
        for r in range(X.shape[0]):
            out[r, i % K] += _brute_shap(t, X[r], F)
    return out.reshape(X.shape[0], K * (F + 1)) if K > 1 else out[:, 0, :]


# ---------------------------------------------------------------------------
# fixtures
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def binary_model(tmp_path_factory):
    """Binary model over NaN-heavy numericals, saved + file-loaded."""
    rng = np.random.default_rng(0)
    X = _nan_matrix(rng, 600, 6)
    y = (np.nan_to_num(X[:, 0]) + np.nan_to_num(X[:, 1]) > 0
         ).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    bst = lgb.train(params, lgb.Dataset(X, label=y, params=params),
                    num_boost_round=10)
    path = str(tmp_path_factory.mktemp("explain") / "binary.txt")
    bst.save_model(path)
    return bst, path


@pytest.fixture(scope="module")
def multiclass_model(tmp_path_factory):
    """Multiclass model with categorical features, saved + file-loaded."""
    rng = np.random.default_rng(1)
    X = _nan_matrix(rng, 600, 4, f_cat=2, cat_lo=0, cat_hi=12)
    y = ((np.nan_to_num(X[:, 0]) > 0).astype(int)
         + (X[:, 4] > 5).astype(int)).astype(np.float64)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 15,
              "verbose": -1, "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, categorical_feature=[4, 5], params=params)
    bst = lgb.train(params, ds, num_boost_round=6)
    path = str(tmp_path_factory.mktemp("explain") / "multi.txt")
    bst.save_model(path)
    return bst, path


def _device_contrib(gbdt, X, num_iteration=None, start_iteration=0):
    """The device path, unconditionally (bypasses the work heuristic)."""
    start, stop = gbdt._iter_window(num_iteration, start_iteration)
    return gbdt._predict_contrib_device(
        np.ascontiguousarray(X, np.float64), start, stop)


# ---------------------------------------------------------------------------
# 1. host-oracle hardening: brute-force Shapley on the reference
#    semantics (categorical bitsets, NaN routing, stumps)
# ---------------------------------------------------------------------------

def test_oracle_matches_brute_force_categorical_nan():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(500, 3))
    X[rng.random(X.shape) < 0.15] = np.nan
    X = np.hstack([X, rng.integers(0, 9, size=(500, 1)).astype(float)])
    y = (np.nan_to_num(X[:, 0]) + (X[:, 3] % 2) > 0.5).astype(float)
    p = {"objective": "binary", "num_leaves": 8, "verbose": -1,
         "min_data_in_leaf": 10}
    bst = lgb.train(p, lgb.Dataset(X, label=y, categorical_feature=[3],
                                   params=p), num_boost_round=4)
    Xt = rng.normal(size=(8, 4))
    Xt[:, 3] = rng.integers(-1, 12, size=8)  # unseen + negative cats
    Xt[0, 0] = np.nan
    Xt[1, 1] = np.nan
    got = predict_contrib(bst._gbdt, Xt)
    want = _brute_contrib(bst._gbdt, Xt)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-9)


def test_oracle_matches_brute_force_nan_default_left():
    rng = np.random.default_rng(8)
    X = rng.normal(size=(800, 3))
    X[rng.random(X.shape) < 0.3] = np.nan
    # NaN predictive of the label forces default-left AND default-right
    # nodes into the same forest
    y = np.where(np.isnan(X[:, 0]), 1.0, (X[:, 0] > 0).astype(float))
    p = {"objective": "regression", "num_leaves": 8, "verbose": -1}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=3)
    Xt = rng.normal(size=(6, 3))
    Xt[rng.random(Xt.shape) < 0.4] = np.nan
    got = predict_contrib(bst._gbdt, Xt)
    want = _brute_contrib(bst._gbdt, Xt)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-9)


def test_oracle_stump_expected_value_only():
    """A single-leaf tree contributes ONLY to the expected-value column
    (reference: PredictContrib skips trees with one leaf)."""
    rng = np.random.default_rng(9)
    X = rng.normal(size=(100, 2))
    p = {"objective": "regression", "num_leaves": 2, "verbose": -1,
         "min_gain_to_split": 1e9}  # no split ever clears the bar
    bst = lgb.train(p, lgb.Dataset(X, label=np.full(100, 1.5), params=p),
                    num_boost_round=2)
    assert all(t.num_leaves == 1 for t in bst._gbdt.models)
    got = predict_contrib(bst._gbdt, X[:5])
    want = _brute_contrib(bst._gbdt, X[:5])
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-12)
    assert np.all(got[:, :2] == 0.0)
    np.testing.assert_allclose(got[:, 2], bst.predict(X[:5]),
                               rtol=0, atol=1e-12)


# ---------------------------------------------------------------------------
# 2. device kernel vs host oracle + local accuracy
# ---------------------------------------------------------------------------

def _check_parity_and_local_accuracy(bst, gbdt, Xt, atol=1e-5):
    want = predict_contrib(gbdt, Xt)
    got = _device_contrib(gbdt, Xt)
    np.testing.assert_allclose(got, want, rtol=0, atol=atol)
    # SHAP local accuracy: per-class contributions sum to the raw score
    K = gbdt.num_tpi
    raw = bst.predict(Xt, raw_score=True)
    s = np.asarray(got).reshape(Xt.shape[0], K, -1).sum(axis=2)
    np.testing.assert_allclose(s[:, 0] if K == 1 else s, raw,
                               rtol=0, atol=atol)


def test_device_matches_oracle_binary_nan(binary_model):
    bst, _ = binary_model
    rng = np.random.default_rng(2)
    _check_parity_and_local_accuracy(bst, bst._gbdt,
                                     _nan_matrix(rng, 80, 6))


def test_device_matches_oracle_multiclass_categorical(multiclass_model):
    bst, _ = multiclass_model
    rng = np.random.default_rng(3)
    # unseen + negative categories exercise the sentinel routing
    Xt = _nan_matrix(rng, 60, 4, f_cat=2, cat_lo=-2, cat_hi=20)
    _check_parity_and_local_accuracy(bst, bst._gbdt, Xt)
    got = _device_contrib(bst._gbdt, Xt)
    assert got.shape == (60, 3 * 7)  # [n, K*(F+1)]


def test_device_matches_oracle_deep_duplicate_features():
    """Few features + deep trees: every path revisits features, so the
    pack-time slot merging is load-bearing."""
    rng = np.random.default_rng(4)
    X = rng.normal(size=(1500, 3))
    y = np.sin(X[:, 0] * 3) + np.cos(X[:, 1] * 2) * X[:, 2]
    p = {"objective": "regression", "num_leaves": 63, "verbose": -1,
         "min_data_in_leaf": 3}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p),
                    num_boost_round=8)
    _check_parity_and_local_accuracy(bst, bst._gbdt,
                                     rng.normal(size=(30, 3)))


def test_device_matches_oracle_file_loaded_no_train_ds(multiclass_model):
    """Counts come from model.txt (internal_count=/leaf_count= lines),
    no training state at all."""
    _, path = multiclass_model
    rng = np.random.default_rng(5)
    Xt = _nan_matrix(rng, 40, 4, f_cat=2, cat_lo=-1, cat_hi=16)
    b2 = lgb.Booster(model_file=path)
    assert b2._gbdt.train_ds is None
    _check_parity_and_local_accuracy(b2, b2._gbdt, Xt)


def test_device_iteration_windows(binary_model):
    bst, _ = binary_model
    g = bst._gbdt
    rng = np.random.default_rng(6)
    Xt = _nan_matrix(rng, 12, 6)
    for ni, si in ((4, 0), (5, 3), (None, 7)):
        want = predict_contrib(g, Xt, ni, si)
        got = _device_contrib(g, Xt, ni, si)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)


def test_predict_contrib_surface_routes_device(binary_model, monkeypatch):
    """Booster.predict(pred_contrib=True) rides the device kernel when
    the work heuristic says so (forced here), host oracle otherwise."""
    bst, _ = binary_model
    rng = np.random.default_rng(10)
    Xt = _nan_matrix(rng, 25, 6)
    want = predict_contrib(bst._gbdt, Xt)
    monkeypatch.setenv("LGBM_TPU_CONTRIB_MIN_WORK", "0")
    got = bst.predict(Xt, pred_contrib=True)
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)
    # a sky-high threshold keeps small inputs on the host oracle exactly
    monkeypatch.setenv("LGBM_TPU_CONTRIB_MIN_WORK", str(10**12))
    host = bst.predict(Xt, pred_contrib=True)
    np.testing.assert_allclose(host, want, rtol=0, atol=0)


def test_explain_requires_cover_counts():
    """A tree dict without counts cannot be packed for TreeSHAP — the
    pack raises instead of emitting garbage fractions."""
    from lightgbm_tpu.explain import tree_path_arrays
    t = dict(num_leaves=2, split_feature=np.zeros(1, np.int32),
             left_child=np.asarray([-1], np.int32),
             right_child=np.asarray([-2], np.int32),
             leaf_value=np.asarray([0.5, -0.5], np.float32),
             internal_count=np.zeros(1, np.int32),
             leaf_count=np.zeros(2, np.int32))
    with pytest.raises(ValueError, match="cover counts"):
        tree_path_arrays(t, 3)


# ---------------------------------------------------------------------------
# 3. serving: session explain, HTTP /explain, buckets, metrics
# ---------------------------------------------------------------------------

def test_session_explain_sync_async_parity(binary_model):
    _, path = binary_model
    rng = np.random.default_rng(11)
    Xt = _nan_matrix(rng, 37, 6)
    want = predict_contrib(lgb.Booster(model_file=path)._gbdt, Xt)
    with PredictorSession(path, max_batch=64) as sess:
        got = sess.explain(Xt)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)
        ticket = sess.submit_explain(Xt)
        got2 = sess.result(ticket, timeout=60)
        np.testing.assert_allclose(got2, want, rtol=0, atol=1e-5)
        # local accuracy against the session's own raw predictions
        raw = sess.predict(Xt, raw_score=True)
        np.testing.assert_allclose(got.sum(axis=1), raw, rtol=0,
                                   atol=1e-5)
        st = sess.stats()
    assert st["explain_armed"] is True
    assert st["explain_requests"] == 2
    assert st["explain_p50_ms"] is not None
    # every explain batch padded to a pow2 bucket of ITS OWN family
    assert all(b & (b - 1) == 0 for b in st["explain_buckets"])


def test_explain_lazy_packing(binary_model):
    """A predict-only session never packs the path metadata (the HBM
    cost gate); the first explain arms it."""
    _, path = binary_model
    rng = np.random.default_rng(12)
    Xt = _nan_matrix(rng, 10, 6)
    with PredictorSession(path, max_batch=32) as sess:
        sess.predict(Xt)
        assert sess.stats()["explain_armed"] is False
        sess.explain(Xt)
        assert sess.stats()["explain_armed"] is True


def test_explain_disabled(binary_model):
    _, path = binary_model
    cfg = {"tpu_explain": False, "objective": "binary"}
    with PredictorSession(path, config=cfg) as sess:
        with pytest.raises(RuntimeError, match="disabled"):
            sess.explain(np.zeros((2, 6)))
        with PredictServer(sess) as server:
            code, body = _post(server.url + "/explain",
                               {"rows": [[0.0] * 6]})
            assert code == 404 and body["error"] == "explain_disabled"


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read())


def test_http_explain_concurrent_mixed_load_bounded_compiles(
        multiclass_model, tmp_path):
    """Concurrent /explain + /predict traffic: parity end to end, the
    explain bucket family bounded by ceil(log2(explain_max_batch))+1
    compiles, and both planes visible in /metrics + the digest."""
    _, path = multiclass_model
    obs.enable(str(tmp_path / "telem"))
    try:
        x_max = 16
        cfg = {"objective": "multiclass", "num_class": 3,
               "tpu_explain_max_batch": x_max,
               "tpu_explain_max_wait_ms": 1.0}
        sess = PredictorSession(path, config=cfg, max_batch=32,
                                max_wait_ms=1.0)
        host = lgb.Booster(model_file=path)
        compiles0 = obs.counter_value("jax/compiles")
        errs = []

        def client(seed):
            rng = np.random.default_rng(seed)
            with_explain = seed % 2 == 0
            for i in range(3):
                n = int(rng.integers(1, 24))
                Xi = _nan_matrix(rng, n, 4, f_cat=2, cat_lo=-1, cat_hi=16)
                path_ = ("/explain" if with_explain and i % 2 == 0
                         else "/predict")
                code, body = _post(server.url + path_, {"rows": Xi.tolist()})
                if code != 200:
                    errs.append((path_, code, body))
                    continue
                if path_ == "/explain":
                    got = np.asarray(body["contributions"])
                    want = predict_contrib(host._gbdt, Xi)
                    if body["num_features"] != 6 or body["num_class"] != 3:
                        errs.append(("shape-meta", body))
                else:
                    got = np.asarray(body["predictions"])
                    want = host.predict(Xi)
                d = float(np.abs(got - want).max())
                if d > 1e-5:
                    errs.append((path_, d))

        with PredictServer(sess) as server:
            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            with urllib.request.urlopen(server.url + "/metrics",
                                        timeout=30) as resp:
                metrics = resp.read().decode()
            with urllib.request.urlopen(server.url + "/health",
                                        timeout=30) as resp:
                health = json.loads(resp.read())
        st = sess.stats()
        sess.close()
        compiles = obs.counter_value("jax/compiles") - compiles0
        assert not errs, errs
        # both bucket families stay inside their own pow2 budgets, and
        # the total compile count inside the summed bound
        x_bound = math.ceil(math.log2(x_max)) + 1
        p_bound = math.ceil(math.log2(32)) + 1
        assert len(st["explain_buckets"]) <= x_bound, st["explain_buckets"]
        assert len(st["buckets"]) <= p_bound
        assert compiles <= x_bound + p_bound
        assert st["explain_requests"] >= 2 and st["explain_ok"] >= 2
        assert st["explain_occupancy"] is None or \
            0 < st["explain_occupancy"] <= 1
        # the explain plane is on the wire: Prometheus + health
        assert 'tpu_serve_explain_requests_total{outcome="ok"}' in metrics
        assert "tpu_serve_explain_latency_ms_bucket" in metrics
        assert health["explain_armed"] is True
        # and in the telemetry digest
        from lightgbm_tpu.obs.report import (load_events, render,
                                             serve_summary, summarize,
                                             validate_events)
        events = load_events(str(tmp_path / "telem"))
        assert not validate_events(events)
        digest = serve_summary(events)
        assert digest["explain"]["requests"] >= 2
        assert digest["explain"]["p99_ms"] is not None
        assert "explain:" in render(summarize(events))
    finally:
        obs.disable()


def test_explain_warmup_precompiles_bucket_family(binary_model):
    _, path = binary_model
    cfg = {"objective": "binary", "tpu_explain_max_batch": 8}
    with PredictorSession(path, config=cfg, max_batch=16) as sess:
        n = sess.warmup_explain()
        st = sess.stats()
    assert n == math.ceil(math.log2(8)) + 1
    assert st["explain_buckets"] == [1, 2, 4, 8]


def test_explain_degrades_to_host_oracle(binary_model, monkeypatch,
                                         tmp_path):
    """A device fault mid-explain falls back to the host recursion —
    requests keep succeeding with identical results."""
    _, path = binary_model
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    rng = np.random.default_rng(13)
    Xt = _nan_matrix(rng, 20, 6)
    want = predict_contrib(lgb.Booster(model_file=path)._gbdt, Xt)
    sess = PredictorSession(path, max_batch=32)

    def boom(bins, span_ctx=None):
        raise RuntimeError("device backend died mid-flight")

    monkeypatch.setattr(sess, "_run_device_explain", boom)
    got = sess.explain(Xt)                       # sync path degrades
    np.testing.assert_allclose(got, want, rtol=0, atol=1e-10)
    ticket = sess.submit_explain(Xt)             # async path follows
    got2 = sess.result(ticket, timeout=60)
    np.testing.assert_allclose(got2, want, rtol=0, atol=1e-10)
    # predict stays on the device: an explain-kernel failure must not
    # degrade the predict plane (its working set is much smaller)
    ref_pred = lgb.Booster(model_file=path).predict(Xt)
    np.testing.assert_allclose(sess.predict(Xt), ref_pred, atol=1e-6)
    st = sess.stats()
    sess.close()
    assert st["explain_degraded"] is True
    assert st["degraded"] is False


def test_explain_reprobe_recovers_explain_plane_only(binary_model,
                                                     monkeypatch,
                                                     tmp_path):
    """The explain reprobe runs the TreeSHAP kernel itself — a healthy
    predict path never re-arms a still-broken explain kernel, and a
    recovered kernel resumes device explanations."""
    _, path = binary_model
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    rng = np.random.default_rng(14)
    Xt = _nan_matrix(rng, 12, 6)
    want = predict_contrib(lgb.Booster(model_file=path)._gbdt, Xt)
    sess = PredictorSession(path, config={"objective": "binary",
                                          "tpu_serve_reprobe_s": 0.05},
                            max_batch=32)
    real = sess._run_device_explain
    boom = {"left": 2}

    def flaky(bins, span_ctx=None):
        if boom["left"] > 0:
            boom["left"] -= 1
            raise RuntimeError("treeshap kernel OOM")
        return real(bins, span_ctx=span_ctx)

    monkeypatch.setattr(sess, "_run_device_explain", flaky)
    np.testing.assert_allclose(sess.explain(Xt), want, atol=1e-5)
    assert sess.stats()["explain_degraded"] is True
    time.sleep(0.06)
    # first call after the interval probes (fails: boom still armed),
    # stays on the host oracle, and does NOT flip the predict plane
    np.testing.assert_allclose(sess.explain(Xt), want, atol=1e-5)
    assert sess.stats()["explain_degraded"] is True
    assert sess.stats()["degraded"] is False
    time.sleep(0.06)
    np.testing.assert_allclose(sess.explain(Xt), want, atol=1e-5)
    st = sess.stats()
    sess.close()
    assert st["explain_degraded"] is False


# ---------------------------------------------------------------------------
# 4. event schemas + cost model
# ---------------------------------------------------------------------------

def test_explain_event_schemas():
    from lightgbm_tpu.obs.report import validate_events
    good = [{"event": "explain_request", "rows": 3, "total_ms": 1.2,
             "ok": True},
            {"event": "explain_batch", "rows": 3, "padded": 4,
             "requests": 1, "queue_rows": 0, "exec_ms": 0.9,
             "degraded": False}]
    assert validate_events(good) == []
    bad = [{"event": "explain_request", "rows": "three", "ok": True}]
    problems = validate_events(bad)
    assert any("rows" in p for p in problems)


def test_stack_forest_with_counts_roundtrip(binary_model):
    """The flag-gated count plumbing (`stack_forest(with_counts=True)` /
    `ServeBinSpace.pack(with_counts=True)`): cover counts ride
    `ForestArrays` only when asked — the serve/contrib paths fold them
    into `ExplainArrays` host-side and stack count-free, so this is the
    API for future device-side cover consumers (e.g. interaction
    values), and predict-only forests never pay the [T, M] HBM cost."""
    from lightgbm_tpu.core.forest import stack_forest
    _, path = binary_model
    with PredictorSession(path) as sess:
        space, trees = sess.space, sess._trees
        dicts = [space.tree_arrays_np(t, with_counts=True) for t in trees]
        cls = np.zeros(len(trees), np.int32)
        fa = stack_forest(dicts, cls, min_words=space.min_words,
                          with_counts=True)
        assert fa.internal_count is not None and fa.leaf_count is not None
        for i, d in enumerate(dicts):
            m = d["internal_count"].shape[0]
            np.testing.assert_array_equal(
                np.asarray(fa.internal_count)[i, :m], d["internal_count"])
            n = d["leaf_count"].shape[0]
            np.testing.assert_array_equal(
                np.asarray(fa.leaf_count)[i, :n], d["leaf_count"])
        packed = space.pack(trees, cls, with_counts=True)
        assert packed.internal_count is not None
        # the predict forest stays count-free by default
        assert sess.forest.internal_count is None
        assert sess.forest.leaf_count is None


def test_shap_cost_model_scales():
    from lightgbm_tpu.ops.treeshap import shap_cost
    f1, b1 = shap_cost(N=64, T=10, L=31, P=8, F=12)
    f2, b2 = shap_cost(N=128, T=10, L=31, P=8, F=12)
    assert f2 == pytest.approx(2 * f1, rel=0.05)  # linear in rows
    f4, _ = shap_cost(N=64, T=10, L=31, P=16, F=12)
    assert f4 > 3.5 * f1                          # ~quadratic in depth
    assert f1 > 0 and b1 > 0 and b2 > b1


# ---------------------------------------------------------------------------
# 6. ranking fixture (ISSUE 13): /explain parity on a lambdarank model
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def rank_model(tmp_path_factory):
    """Lambdarank model (ragged queries) saved + file-loaded — the
    serving-plane ranking fixture's explain twin."""
    rng = np.random.default_rng(21)
    sizes = np.concatenate([rng.integers(1, 30, size=25), [1, 80]])
    N = int(sizes.sum())
    X = rng.normal(size=(N, 8))
    y = rng.integers(0, 5, size=N).astype(np.float64)
    params = {"objective": "lambdarank", "metric": "ndcg",
              "num_leaves": 15, "min_data_in_leaf": 5, "verbose": -1}
    ds = lgb.Dataset(X, label=y, group=sizes, params=params)
    bst = lgb.train(params, ds, num_boost_round=12)
    path = str(tmp_path_factory.mktemp("explain") / "rank.txt")
    bst.save_model(path)
    return bst, path


def test_session_explain_rank_model_parity(rank_model):
    """A served lambdarank model explains to host-oracle parity, with
    SHAP local accuracy against its own raw ranking scores — the same
    contract the classification fixtures pin, on the ranking batch
    shape (one query's doc list per request)."""
    _, path = rank_model
    rng = np.random.default_rng(22)
    Xq = rng.normal(size=(23, 8))       # one query's docs
    want = predict_contrib(lgb.Booster(model_file=path)._gbdt, Xq)
    with PredictorSession(path, max_batch=32) as sess:
        got = sess.explain(Xq)
        np.testing.assert_allclose(got, want, rtol=0, atol=1e-5)
        # local accuracy: contributions sum to the raw ranking score
        raw = sess.predict(Xq, raw_score=True)
        np.testing.assert_allclose(got.sum(axis=1), raw, rtol=0,
                                   atol=1e-5)
        # mixed predict+explain traffic on the same session
        ticket = sess.submit(Xq)
        xticket = sess.submit_explain(Xq[:5])
        np.testing.assert_allclose(sess.result(ticket, timeout=60), raw,
                                   rtol=0, atol=1e-6)
        np.testing.assert_allclose(sess.result(xticket, timeout=60),
                                   want[:5], rtol=0, atol=1e-5)
        st = sess.stats()
    assert st["explain_armed"] is True and st["degraded"] is False
