"""Fault tolerance (lightgbm_tpu/robust/): atomic checkpoint/resume
differentials, the device-wedge watchdog, and the fault-injection
harness.

The headline proof is the crash-resume differential: train N straight
vs train-to-crash + resume-to-N must produce BIT-IDENTICAL model text
(forest, leaf values, counts — everything except the parameters block,
which legitimately differs by the checkpoint knobs).  RNG state
(bagging, feature fraction, DART drops), score arrays, and the eval
history all ride the checkpoint, so the differential covers the whole
resume surface the way the sequential-split oracle covers the wave
apply.
"""
from __future__ import annotations

import glob
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.robust import (CheckpointManager, DeviceWedgedError,
                                 FaultInjected, FaultTransient,
                                 config_digest, faults)
from lightgbm_tpu.robust.watchdog import (DeviceGuard, backoff_delays,
                                          classify_error, classify_text)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

rng = np.random.default_rng(7)
N = 600
X = rng.normal(size=(N, 8))
y = (X[:, 0] + 0.5 * X[:, 1] + 0.1 * rng.normal(size=N) > 0
     ).astype(np.float64)
XV = rng.normal(size=(200, 8))
YV = (XV[:, 0] + 0.5 * XV[:, 1] > 0).astype(np.float64)

BASE = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
        "verbose": -1, "seed": 1}


@pytest.fixture(autouse=True)
def _disarm_faults():
    faults.disarm()
    yield
    faults.disarm()


def _model(booster):
    """Model text minus the parameters block (the checkpoint knobs
    legitimately differ between the straight and the resumed run)."""
    return booster.model_to_string(num_iteration=-1).split(
        "\nparameters:")[0]


def _mk(params):
    ds = lgb.Dataset(X, label=y, params=dict(params))
    vs = lgb.Dataset(XV, label=YV, reference=ds)
    return ds, vs


def _diff_resume(extra, n=12, crash=7, freq=5, es=None, tmp=None):
    """Straight-vs-crash-resume differential; returns (straight booster,
    resumed booster, checkpoint dir)."""
    p = dict(BASE)
    p.update(extra)
    kw = {"verbose_eval": False}
    if es:
        kw["early_stopping_rounds"] = es
    ds, vs = _mk(p)
    b1 = lgb.train(dict(p), ds, num_boost_round=n, valid_sets=[vs], **kw)
    p2 = dict(p, tpu_checkpoint_dir=str(tmp), tpu_checkpoint_freq=freq)
    ds, vs = _mk(p)
    lgb.train(dict(p2), ds, num_boost_round=crash, valid_sets=[vs], **kw)
    ds, vs = _mk(p)
    b2 = lgb.train(dict(p2), ds, num_boost_round=n, valid_sets=[vs], **kw)
    return b1, b2, str(tmp)


# ---------------------------------------------------------------------------
# crash-resume differentials: bit-identical models
# ---------------------------------------------------------------------------

def test_resume_bit_identical_bagging(tmp_path):
    b1, b2, ck = _diff_resume(
        {"bagging_fraction": 0.7, "bagging_freq": 3,
         "feature_fraction": 0.8}, tmp=tmp_path)
    assert _model(b1) == _model(b2)
    # the crash run left a checkpoint behind; the resume run added more
    assert len(glob.glob(os.path.join(ck, "ckpt_*"))) >= 1


def test_resume_bit_identical_goss(tmp_path):
    b1, b2, _ = _diff_resume(
        {"boosting": "goss", "learning_rate": 0.5, "top_rate": 0.3,
         "other_rate": 0.2}, tmp=tmp_path)
    assert _model(b1) == _model(b2)


def test_resume_bit_identical_dart(tmp_path):
    b1, b2, _ = _diff_resume(
        {"boosting": "dart", "drop_rate": 0.5, "skip_drop": 0.2},
        tmp=tmp_path)
    assert _model(b1) == _model(b2)


def test_resume_bit_identical_early_stopping(tmp_path):
    b1, b2, _ = _diff_resume({"learning_rate": 0.3}, n=40, crash=9,
                             freq=4, es=3, tmp=tmp_path)
    assert b1.best_iteration == b2.best_iteration
    assert _model(b1) == _model(b2)


def test_resume_bit_identical_two_device_mesh(tmp_path):
    b1, b2, _ = _diff_resume(
        {"tree_learner": "data", "tpu_mesh_shape": "data:2"},
        tmp=tmp_path)
    assert _model(b1) == _model(b2)


def test_resume_restores_eval_history(tmp_path):
    """record_evaluation continues mid-stream: the resumed run's evals
    dict must equal the straight run's for every iteration, including
    the pre-crash ones it never computed itself."""
    p = dict(BASE, learning_rate=0.3)
    ds, vs = _mk(p)
    ev1: dict = {}
    lgb.train(dict(p), ds, num_boost_round=10, valid_sets=[vs],
              verbose_eval=False, evals_result=ev1)
    p2 = dict(p, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=4)
    ds, vs = _mk(p)
    lgb.train(dict(p2), ds, num_boost_round=6, valid_sets=[vs],
              verbose_eval=False)
    ds, vs = _mk(p)
    ev2: dict = {}
    lgb.train(dict(p2), ds, num_boost_round=10, valid_sets=[vs],
              verbose_eval=False, evals_result=ev2)
    assert ev1 == ev2


# ---------------------------------------------------------------------------
# checkpoint mechanics: atomicity, validation, pruning, config digest
# ---------------------------------------------------------------------------

def test_corrupt_newest_checkpoint_falls_back(tmp_path):
    p = dict(BASE, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=3)
    ds, vs = _mk(p)
    lgb.train(dict(p), ds, num_boost_round=7, valid_sets=[vs],
              verbose_eval=False)
    cks = sorted(glob.glob(os.path.join(str(tmp_path), "ckpt_*")))
    assert len(cks) == 2  # iterations 3 and 6
    with open(os.path.join(cks[-1], "model.txt"), "a") as fh:
        fh.write("corruption")
    mgr = CheckpointManager(str(tmp_path))
    peeked = mgr.peek(Config.from_params(p))
    assert peeked is not None
    assert peeked[0] == cks[0]  # fell back to the older valid one
    assert peeked[1]["iteration"] == 3


def test_orphan_tmp_dirs_ignored_and_swept(tmp_path):
    orphan = tmp_path / ".tmp-9999-5"
    orphan.mkdir()
    (orphan / "model.txt").write_text("partial")
    p = dict(BASE, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=4)
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.peek(Config.from_params(p)) is None  # orphan is invisible
    ds, vs = _mk(p)
    lgb.train(dict(p), ds, num_boost_round=5, valid_sets=[vs],
              verbose_eval=False)
    assert not orphan.exists()  # swept by the first real save


def test_checkpoint_pruning_keeps_newest(tmp_path):
    p = dict(BASE, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=2,
             tpu_checkpoint_keep=2)
    ds, vs = _mk(p)
    lgb.train(dict(p), ds, num_boost_round=9, valid_sets=[vs],
              verbose_eval=False)
    names = sorted(os.path.basename(d) for d in
                   glob.glob(os.path.join(str(tmp_path), "ckpt_*")))
    assert names == ["ckpt_00000006", "ckpt_00000008"]


def test_stale_foreign_config_checkpoints_pruned(tmp_path):
    """A reused checkpoint dir: a previous run's HIGHER-iteration
    checkpoints under a different config must not shadow (and then
    out-prune) the fresh run's — after the fresh run saves, its own
    checkpoint is the resumable one."""
    old = dict(BASE, num_leaves=15, tpu_checkpoint_dir=str(tmp_path),
               tpu_checkpoint_freq=5)
    ds, vs = _mk(old)
    lgb.train(dict(old), ds, num_boost_round=11, valid_sets=[vs],
              verbose_eval=False)  # leaves ckpt_00000005/10 under old cfg
    new = dict(BASE, tpu_checkpoint_dir=str(tmp_path),
               tpu_checkpoint_freq=3)
    ds, vs = _mk(new)
    lgb.train(dict(new), ds, num_boost_round=4, valid_sets=[vs],
              verbose_eval=False)  # digest mismatch -> fresh + ckpt at 3
    names = sorted(os.path.basename(d) for d in
                   glob.glob(os.path.join(str(tmp_path), "ckpt_*")))
    assert names == ["ckpt_00000003"]  # stale foreign ones removed
    mgr = CheckpointManager(str(tmp_path))
    peeked = mgr.peek(Config.from_params(new))
    assert peeked is not None and peeked[1]["iteration"] == 3


def test_resume_bit_identical_learning_rate_schedule(tmp_path):
    """A reset_parameter(learning_rate=[...]) schedule across a crash:
    the first resumed iteration must train at the SCHEDULED rate, not
    the checkpoint-restored one."""
    # the silent-skip case: params carry learning_rate=0.1 and the
    # schedule value AT the resume iteration is also 0.1, while the
    # restored shrinkage is 0.2 — an unreconciled reset_parameter sees
    # "no change" and trains the first resumed iteration at 0.2
    p = dict(BASE, learning_rate=0.1)
    n = 8
    lrs = [0.2, 0.2, 0.2, 0.2, 0.1, 0.1, 0.1, 0.1]
    ds, vs = _mk(p)
    b1 = lgb.train(dict(p), ds, num_boost_round=n, valid_sets=[vs],
                   verbose_eval=False, learning_rates=list(lrs))
    p2 = dict(p, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=2)
    # crash from a USER callback at iteration 5 (a wedge would write a
    # boundary checkpoint carrying the already-reset rate, hiding the
    # bug): the newest checkpoint is the periodic one at iteration 4,
    # whose restored shrinkage (0.2, from iteration 3) differs from the
    # schedule at the resume point (0.1)

    class _Boom(Exception):
        pass

    def boom(env):
        if env.iteration == 5:
            raise _Boom()
    boom.order = 99
    ds, vs = _mk(p)
    with pytest.raises(_Boom):
        lgb.train(dict(p2), ds, num_boost_round=n, valid_sets=[vs],
                  verbose_eval=False, learning_rates=list(lrs),
                  callbacks=[boom])
    ds, vs = _mk(p)
    b2 = lgb.train(dict(p2), ds, num_boost_round=n, valid_sets=[vs],
                   verbose_eval=False, learning_rates=list(lrs))
    assert _model(b1) == _model(b2)


def test_config_mismatch_refuses_resume(tmp_path):
    p = dict(BASE, tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=3)
    ds, vs = _mk(p)
    lgb.train(dict(p), ds, num_boost_round=4, valid_sets=[vs],
              verbose_eval=False)
    mgr = CheckpointManager(str(tmp_path))
    assert mgr.peek(Config.from_params(p)) is not None
    changed = dict(p, num_leaves=15)
    assert mgr.peek(Config.from_params(changed)) is None


def test_config_digest_ignores_operational_knobs():
    a = Config.from_params(dict(BASE))
    b = Config.from_params(dict(BASE, tpu_checkpoint_dir="/x",
                                tpu_telemetry="/y", output_model="z.txt",
                                tpu_watchdog=True))
    c = Config.from_params(dict(BASE, learning_rate=0.42))
    assert config_digest(a) == config_digest(b)
    assert config_digest(a) != config_digest(c)


def test_checkpoint_events_validate(tmp_path):
    from lightgbm_tpu.obs.report import (load_events, robust_summary,
                                         validate_events)
    sink = tmp_path / "telem"
    obs.enable(str(sink))
    try:
        p = dict(BASE, tpu_checkpoint_dir=str(tmp_path / "ck"),
                 tpu_checkpoint_freq=3)
        ds, vs = _mk(p)
        lgb.train(dict(p), ds, num_boost_round=4, valid_sets=[vs],
                  verbose_eval=False)
        ds, vs = _mk(p)
        lgb.train(dict(p), ds, num_boost_round=6, valid_sets=[vs],
                  verbose_eval=False)
    finally:
        obs.disable()
    events = load_events(str(sink))
    assert validate_events(events) == []
    r = robust_summary(events)
    assert r["checkpoints"] >= 2
    assert r["restores"] == 1
    assert r["resumed_from_iteration"] == 3
    assert r["last_checkpoint"]["iteration"] == 6


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------

def test_fault_spec_grammar():
    specs = faults.parse_spec(
        "device_execute:transient@iter=3&n=2;"
        "serve_device:raise;collective:sleep=0.5@call=2&p=0.5")
    assert [s.point for s in specs] == ["device_execute", "serve_device",
                                       "collective"]
    assert specs[0].action == "transient" and specs[0].iter_ == 3 \
        and specs[0].remaining == 2
    assert specs[1].action == "raise" and specs[1].remaining == 1
    assert specs[2].action == "sleep" and specs[2].arg == 0.5 \
        and specs[2].call == 2 and specs[2].p == 0.5
    for bad in ("nocolon", "p:unknown_action", "p:raise@call"):
        with pytest.raises(ValueError):
            faults.parse_spec(bad)


def test_fault_fires_deterministically():
    faults.configure("pt:transient@call=2&n=1")
    faults.check("pt")                      # call 1: no fire
    with pytest.raises(FaultTransient):
        faults.check("pt")                  # call 2: fires
    faults.check("pt")                      # n exhausted
    faults.configure("pt:raise@iter=5")
    faults.check("pt", iteration=4)
    with pytest.raises(FaultInjected):
        faults.check("pt", iteration=5)


def test_fault_probability_seeded():
    def fires(seed):
        faults.configure("pt:raise@p=0.5&n=-1", seed=seed)
        out = []
        for _ in range(32):
            try:
                faults.check("pt")
                out.append(0)
            except FaultInjected:
                out.append(1)
        return out
    a, b, c = fires(3), fires(3), fires(4)
    assert a == b            # same seed -> identical schedule
    assert a != c            # different seed -> different schedule
    assert 0 < sum(a) < 32   # actually probabilistic


# ---------------------------------------------------------------------------
# watchdog: classification, backoff, retry, policies, stall
# ---------------------------------------------------------------------------

def test_classify_error_patterns():
    assert classify_error(RuntimeError("UNAVAILABLE: socket closed")) \
        == "transient"
    assert classify_error(RuntimeError("RESOURCE_EXHAUSTED: hbm")) \
        == "transient"
    assert classify_error(FaultTransient("x")) == "transient"
    assert classify_error(FaultInjected("x")) == "fatal"
    assert classify_error(ValueError("bad shape")) == "fatal"
    assert classify_text("", timed_out=True) == "wedge"
    assert classify_text("DEADLINE_EXCEEDED while waiting") == "transient"
    assert classify_text("AssertionError: 1 != 2") is None


def test_backoff_deterministic_bounded():
    a = backoff_delays(5, base_s=0.1, cap_s=0.8, seed=9)
    b = backoff_delays(5, base_s=0.1, cap_s=0.8, seed=9)
    assert a == b
    assert all(d <= 0.8 * 1.25 + 1e-9 for d in a)
    assert a[1] > a[0]  # exponential growth below the cap


def test_guard_retries_transient_then_succeeds():
    faults.configure("pt:transient@n=2")
    guard = DeviceGuard(policy="retry", retries=3, backoff_base_s=0.001,
                        stall_timeout_s=-1.0)
    calls = []
    out = guard.run(lambda: calls.append(1) or "ok", point="pt")
    assert out == "ok"
    assert len(calls) == 1          # the two faulted attempts never ran fn
    assert guard.retry_count == 2


def test_guard_abort_policy_no_retry():
    faults.configure("pt:transient@n=-1")
    guard = DeviceGuard(policy="abort", retries=3, stall_timeout_s=-1.0)
    with pytest.raises(DeviceWedgedError):
        guard.run(lambda: "never", point="pt")


def test_guard_fallback_reexecutes():
    faults.configure("pt:raise")
    guard = DeviceGuard(policy="fallback", retries=0, stall_timeout_s=-1.0)
    assert guard.run(lambda: 42, point="pt") == 42


def test_guard_inactive_is_passthrough():
    guard = DeviceGuard(policy="retry", enabled=False)
    assert not guard.active
    assert guard.run(lambda: 7) == 7


def test_guard_stall_stamped_in_flight_ring():
    obs.enable_flight(32)
    guard = DeviceGuard(policy="retry", enabled=True, stall_timeout_s=0.05)
    guard.run(lambda: time.sleep(0.15) or 1, point="slowpt")
    stalls = [e for e in obs.flight_snapshot()
              if e.get("event") == "device_stall"
              and e.get("point") == "slowpt"]
    assert len(stalls) == 1
    assert stalls[0]["deadline_s"] == 0.05


def test_train_wedge_abort_writes_boundary_checkpoint(tmp_path):
    """A fatal device fault mid-train under abort: DeviceWedgedError +
    a rolled-back boundary checkpoint that resumes bit-exactly."""
    p = dict(BASE, bagging_fraction=0.8, bagging_freq=2)
    ds, vs = _mk(p)
    b_ref = lgb.train(dict(p), ds, num_boost_round=6, valid_sets=[vs],
                      verbose_eval=False)
    faults.configure("device_execute:raise@iter=3")
    p2 = dict(p, tpu_on_device_error="abort",
              tpu_checkpoint_dir=str(tmp_path), tpu_checkpoint_freq=0)
    ds, vs = _mk(p2)
    with pytest.raises(DeviceWedgedError):
        lgb.train(dict(p2), ds, num_boost_round=6, valid_sets=[vs],
                  verbose_eval=False)
    faults.disarm()
    cks = glob.glob(os.path.join(str(tmp_path), "ckpt_*"))
    assert len(cks) == 1 and cks[0].endswith("ckpt_00000003")
    ds, vs = _mk(p2)
    b2 = lgb.train(dict(p2), ds, num_boost_round=6, valid_sets=[vs],
                   verbose_eval=False)
    assert _model(b_ref) == _model(b2)


def test_train_transient_retry_bit_identical():
    p = dict(BASE)
    ds, vs = _mk(p)
    b_ref = lgb.train(dict(p), ds, num_boost_round=5, valid_sets=[vs],
                      verbose_eval=False)
    faults.configure("device_execute:transient@iter=2")
    ds, vs = _mk(p)
    b2 = lgb.train(dict(p), ds, num_boost_round=5, valid_sets=[vs],
                   verbose_eval=False)
    assert _model(b_ref) == _model(b2)


# ---------------------------------------------------------------------------
# serve: degradation is no longer a one-way latch
# ---------------------------------------------------------------------------

def _serve_booster():
    ds = lgb.Dataset(X, label=y, params=dict(BASE))
    return lgb.train(dict(BASE), ds, num_boost_round=4, verbose_eval=False)


def test_serve_reprobe_recovers():
    from lightgbm_tpu.serve import PredictorSession
    from lightgbm_tpu.serve.metrics import (parse_prometheus,
                                            render_prometheus)
    bst = _serve_booster()
    ref = bst.predict(X[:16])
    faults.configure("serve_device:raise@call=1")
    with PredictorSession(bst, config=dict(
            BASE, tpu_serve_reprobe_s=0.05,
            tpu_serve_max_batch=64)) as sess:
        out1 = sess.predict(X[:16])
        st = sess.stats()
        assert st["degraded"] and st["degraded_transitions"] == 1
        np.testing.assert_allclose(out1, ref, atol=1e-6)
        prom = parse_prometheus(render_prometheus(sess))
        assert prom["tpu_serve_degraded"] == 1.0
        time.sleep(0.06)
        out2 = sess.predict(X[:16])
        st = sess.stats()
        assert not st["degraded"] and st["recoveries"] == 1
        np.testing.assert_allclose(out2, ref, atol=1e-6)
        prom = parse_prometheus(render_prometheus(sess))
        assert prom["tpu_serve_degraded"] == 0.0
        assert prom["tpu_serve_degraded_transitions_total"] == 1.0
        assert prom["tpu_serve_recoveries_total"] == 1.0


def test_serve_reprobe_zero_keeps_latch():
    from lightgbm_tpu.serve import PredictorSession
    bst = _serve_booster()
    faults.configure("serve_device:raise@call=1")
    with PredictorSession(bst, config=dict(
            BASE, tpu_serve_reprobe_s=0.0,
            tpu_serve_max_batch=64)) as sess:
        sess.predict(X[:8])
        assert sess.stats()["degraded"]
        time.sleep(0.05)
        sess.predict(X[:8])
        assert sess.stats()["degraded"]  # 0 disables re-probing


def test_serve_health_recovers_over_http():
    from lightgbm_tpu.serve import PredictorSession, PredictServer
    import urllib.request
    bst = _serve_booster()
    faults.configure("serve_device:raise@call=1")
    sess = PredictorSession(bst, config=dict(
        BASE, tpu_serve_reprobe_s=0.05, tpu_serve_max_batch=64))
    with PredictServer(sess) as srv:
        body = json.dumps({"rows": X[:4].tolist()}).encode()
        req = urllib.request.Request(srv.url + "/predict", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        urllib.request.urlopen(req, timeout=10).read()
        health = json.loads(urllib.request.urlopen(
            srv.url + "/health", timeout=10).read())
        assert health["status"] == "degraded"
        time.sleep(0.06)
        urllib.request.urlopen(req, timeout=10).read()
        health = json.loads(urllib.request.urlopen(
            srv.url + "/health", timeout=10).read())
        assert health["status"] == "ok"
        assert health["recoveries"] == 1


# ---------------------------------------------------------------------------
# graceful preemption: SIGTERM mid-train -> checkpoint -> resume
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
import lightgbm_tpu as lgb

data = np.load(sys.argv[1])
ckpt = sys.argv[2]
p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
     "verbose": -1, "seed": 1, "bagging_fraction": 0.8, "bagging_freq": 2,
     "tpu_checkpoint_dir": ckpt, "tpu_checkpoint_freq": 2}

def beat(env):
    print(f"ITER {env.iteration + 1}", flush=True)
    time.sleep(0.15)
beat.order = 99

ds = lgb.Dataset(data["X"], label=data["y"], params=p)
print("READY", flush=True)
lgb.train(p, ds, num_boost_round=12, verbose_eval=False, callbacks=[beat])
print("FINISHED", flush=True)
"""


@pytest.mark.slow
def test_sigterm_checkpoints_and_resumes(tmp_path):
    """Kill a training subprocess mid-run: it must write a final
    checkpoint and exit 143; resuming in-process must reproduce the
    uninterrupted model bit-exactly."""
    data = tmp_path / "data.npz"
    np.savez(data, X=X, y=y)
    ck = tmp_path / "ck"
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    proc = subprocess.Popen([sys.executable, "-c", _CHILD, str(data),
                             str(ck)], stdout=subprocess.PIPE, text=True,
                            env=env, cwd=REPO)
    try:
        deadline = time.time() + 300
        seen = 0
        for line in proc.stdout:
            if line.startswith("ITER"):
                seen = int(line.split()[1])
                if seen >= 3:
                    proc.send_signal(signal.SIGTERM)
                    break
            assert time.time() < deadline
        rc = proc.wait(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert seen >= 3
    assert rc == 143  # 128 + SIGTERM: graceful-preemption exit
    cks = glob.glob(os.path.join(str(ck), "ckpt_*"))
    assert cks, "preemption checkpoint missing"

    p = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1, "seed": 1, "bagging_fraction": 0.8,
         "bagging_freq": 2}
    ds = lgb.Dataset(X, label=y, params=dict(p))
    b_ref = lgb.train(dict(p), ds, num_boost_round=12, verbose_eval=False)
    p2 = dict(p, tpu_checkpoint_dir=str(ck), tpu_checkpoint_freq=2)
    ds = lgb.Dataset(X, label=y, params=dict(p2))
    b2 = lgb.train(dict(p2), ds, num_boost_round=12, verbose_eval=False)
    assert _model(b_ref) == _model(b2)


# ---------------------------------------------------------------------------
# tools: wedge-retry path + fault-matrix plumbing
# ---------------------------------------------------------------------------

def _import_tool(name):
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        return __import__(name)
    finally:
        sys.path.remove(tools)


def test_tpu_window_wedge_retry_recovers():
    """A leg that dies with a transient runtime error once and succeeds
    on retry is stamped wedge_retries=1/recovered and the window is NOT
    abandoned."""
    tw = _import_tool("tpu_window")
    calls = {"n": 0}

    def runner(argv, **kw):
        import types
        calls["n"] += 1
        if calls["n"] == 1:
            return types.SimpleNamespace(
                returncode=1, stdout="",
                stderr="RuntimeError: UNAVAILABLE: backend wedge")
        return types.SimpleNamespace(returncode=0,
                                     stdout='{"value": 1}\n', stderr="")

    legs = [{"name": "bench", "argv": ["python", "bench.py"], "env": {},
             "parse_json": True}]
    res = tw.run_legs(legs, runner=runner, timeout=10, wedge_retries=2,
                      backoff_s=0.01)
    rec = res["bench"]
    assert rec["rc"] == 0
    assert rec["wedge_retries"] == 1
    assert rec["wedge_class"] == "transient"
    assert rec["recovered"] is True
    assert rec["parsed"] == {"value": 1}
    assert calls["n"] == 2


def test_tpu_window_unrecovered_leg_not_counted_as_recovered():
    """A leg that retries and STILL fails must not contribute to the
    round-level wedge_retries stamp — the round is broken, not
    recovered."""
    tw = _import_tool("tpu_window")

    def runner(argv, **kw):
        import types
        return types.SimpleNamespace(
            returncode=1, stdout="",
            stderr="RuntimeError: UNAVAILABLE: backend wedge")

    legs = [{"name": "bench", "argv": ["python", "bench.py"], "env": {},
             "parse_json": False}]
    res = tw.run_legs(legs, runner=runner, timeout=10, wedge_retries=2,
                      backoff_s=0.01)
    rec = res["bench"]
    assert rec["rc"] == 1
    assert rec["wedge_retries"] == 2
    assert rec["recovered"] is False
    # the round-level stamp counts only RECOVERED legs' retries
    total = sum(r.get("wedge_retries", 0) for r in res.values()
                if r.get("recovered"))
    assert total == 0


def test_tpu_window_real_failure_not_retried():
    tw = _import_tool("tpu_window")
    calls = {"n": 0}

    def runner(argv, **kw):
        import types
        calls["n"] += 1
        return types.SimpleNamespace(returncode=1, stdout="",
                                     stderr="AssertionError: wrong value")

    legs = [{"name": "bench", "argv": ["python", "bench.py"], "env": {},
             "parse_json": False}]
    res = tw.run_legs(legs, runner=runner, timeout=10, wedge_retries=3,
                      backoff_s=0.01)
    assert res["bench"]["rc"] == 1
    assert "wedge_retries" not in res["bench"]
    assert calls["n"] == 1


def test_bench_history_flags_recovered_rounds(tmp_path):
    bh = _import_tool("bench_history")
    # no "backend" field: bench.py emits it only on degraded rounds,
    # which take the separate canary path
    rec = {"n": 1, "kind": "manual_window", "wedge_retries": 2,
           "parsed": {"rows": 1000, "iters": 5, "num_leaves": 31,
                      "max_bin": 255, "value": 2.5,
                      "unit": "row_iters_per_s"}}
    path = tmp_path / "BENCH_manual_r01.json"
    path.write_text(json.dumps(rec))
    row = bh.load_round(str(path))
    assert row["recovered"] == 2
    assert "recovered after 2 wedge retries" in row["note"]
    assert row["metrics"]["wedge_retries"] == 2.0
    # a clean round carries no flag
    rec2 = dict(rec, wedge_retries=0)
    path2 = tmp_path / "BENCH_manual_r02.json"
    path2.write_text(json.dumps(rec2))
    assert "recovered" not in bh.load_round(str(path2))


def test_run_suite_faults_tier_stubbed():
    rs = _import_tool("run_suite")

    def fake(argv, **kw):
        import types
        line = json.dumps({"kind": "fault_matrix", "ok": True,
                           "checks": {"a": True, "b": True}})
        return types.SimpleNamespace(returncode=0, stdout=line + "\n",
                                     stderr="")

    res = rs.run_tool_smoke("faults", 60, runner=fake)
    assert res["ok"] is True
    assert res["counts"] == {"passed": 2, "failed": 0}
    assert res["cmd"] == "tools/fault_matrix.py --json"
