"""Continued training, init_model, and refit
(reference: boosting.cpp:35-69, gbdt.cpp:298-321, basic.py:2547)."""
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _problem(n=1500, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.2 * rng.normal(size=n) > 0).astype(float)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
          "min_data_in_leaf": 5, "verbose": -1}


def test_init_model_booster_equals_uninterrupted():
    """train 10 then continue 10 == train 20 in one go (no bagging, so the
    RNG stream doesn't matter)."""
    X, y = _problem()
    ds = lgb.Dataset(X, label=y, params=PARAMS)
    b20 = lgb.train(PARAMS, ds, num_boost_round=20)

    ds1 = lgb.Dataset(X, label=y, params=PARAMS)
    b10 = lgb.train(PARAMS, ds1, num_boost_round=10)
    assert b10.num_trees() == 10
    ds2 = lgb.Dataset(X, label=y, params=PARAMS)
    b_cont = lgb.train(PARAMS, ds2, num_boost_round=10, init_model=b10)
    assert b_cont.num_trees() == 20
    assert b_cont.current_iteration() == 20
    np.testing.assert_allclose(b_cont.predict(X), b20.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_init_model_file_roundtrip(tmp_path):
    """save after 10, load the FILE as init_model, continue — same as the
    booster-object path up to text-serialization rounding."""
    X, y = _problem(seed=1)
    ds1 = lgb.Dataset(X, label=y, params=PARAMS)
    b10 = lgb.train(PARAMS, ds1, num_boost_round=10)
    path = tmp_path / "m10.txt"
    b10.save_model(str(path))

    ds2 = lgb.Dataset(X, label=y, params=PARAMS)
    b_cont = lgb.train(PARAMS, ds2, num_boost_round=10, init_model=str(path))
    assert b_cont.num_trees() == 20

    ds3 = lgb.Dataset(X, label=y, params=PARAMS)
    b20 = lgb.train(PARAMS, ds3, num_boost_round=20)
    np.testing.assert_allclose(b_cont.predict(X), b20.predict(X),
                               rtol=1e-4, atol=1e-6)


def test_init_model_multiclass():
    rng = np.random.default_rng(2)
    X = rng.normal(size=(900, 5))
    y = (X[:, 0] > 0).astype(int) + (X[:, 1] > 0.5).astype(int)
    params = {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
              "verbose": -1}
    ds1 = lgb.Dataset(X, label=y.astype(float), params=params)
    b5 = lgb.train(params, ds1, num_boost_round=5)
    ds2 = lgb.Dataset(X, label=y.astype(float), params=params)
    bc = lgb.train(params, ds2, num_boost_round=5, init_model=b5)
    assert bc.num_trees() == 30  # 10 iters x 3 classes
    ds3 = lgb.Dataset(X, label=y.astype(float), params=params)
    b10 = lgb.train(params, ds3, num_boost_round=10)
    np.testing.assert_allclose(bc.predict(X), b10.predict(X),
                               rtol=1e-5, atol=1e-6)


def test_refit_moves_leaf_values_toward_new_data():
    X, y = _problem(seed=3)
    ds = lgb.Dataset(X, label=y, params=PARAMS)
    bst = lgb.train(PARAMS, ds, num_boost_round=10)

    # refit on new data drawn from a SHIFTED distribution
    X2, y2 = _problem(seed=4)
    y2 = 1.0 - y2  # inverted labels: leaf values must move
    rf = bst.refit(X2, y2, decay_rate=0.5)
    assert rf.num_trees() == bst.num_trees()
    # same structures
    t_old = bst.model_to_string()
    t_new = rf.model_to_string()
    feats = lambda txt: [l for l in txt.splitlines()
                         if l.startswith("split_feature=")]
    assert feats(t_old) == feats(t_new)
    # predictions moved toward the new labels
    from sklearn.metrics import roc_auc_score
    auc_old = roc_auc_score(y2, bst.predict(X2))
    auc_new = roc_auc_score(y2, rf.predict(X2))
    assert auc_new > auc_old

    # decay_rate=1.0 keeps the model unchanged
    rf1 = bst.refit(X2, y2, decay_rate=1.0)
    np.testing.assert_allclose(rf1.predict(X), bst.predict(X), atol=1e-9)


def test_refit_requires_objective():
    X, y = _problem(seed=5)
    ds = lgb.Dataset(X, label=y, params=PARAMS)
    bst = lgb.train(dict(PARAMS), ds, num_boost_round=3)
    gb = bst._gbdt
    obj, gb.objective = gb.objective, None
    try:
        with pytest.raises(lgb.LightGBMError):
            bst.refit(X, y)
    finally:
        gb.objective = obj


def test_init_model_with_now_trivial_feature():
    """A loaded tree splitting on a feature that is CONSTANT in the new
    dataset must replay exactly: every row takes the side the constant
    decides in value space (the reference keeps trivial features binned, so
    DataToBin handles this implicitly)."""
    X, y = _problem(seed=7)
    ds1 = lgb.Dataset(X, label=y, params=PARAMS)
    b1 = lgb.train(PARAMS, ds1, num_boost_round=8)
    used = np.flatnonzero(b1._gbdt.feature_importance("split") > 0)
    f = int(used[0])

    # new data: feature f frozen at a constant that sends rows LEFT or
    # RIGHT depending on the node; replay must equal host prediction
    X2 = X.copy()
    X2[:, f] = float(np.quantile(X[:, f], 0.25))
    y2 = y
    ds2 = lgb.Dataset(X2, label=y2, params=PARAMS)
    bc = lgb.train(PARAMS, ds2, num_boost_round=1, init_model=b1)
    gb = bc._gbdt
    # the continued model's first 8 trees replayed onto scores must match
    # host value-space prediction of the ORIGINAL model on X2
    import jax.numpy as jnp
    want = b1.predict(X2, raw_score=True)
    # replay check: rebuild scores from scratch through _tree_to_device
    score = np.zeros(len(X2))
    from lightgbm_tpu.core.predict import predict_leaf_bins
    for t in list(b1._gbdt.models):
        arrs = gb._tree_to_device(t)
        leaf = np.asarray(predict_leaf_bins(arrs, gb._bins, gb.meta))
        score += np.asarray(arrs.leaf_value)[leaf]
    np.testing.assert_allclose(score, want, atol=1e-5)


REF_CLI = "/tmp/refsrc/lightgbm"


@pytest.mark.skipif(not os.path.exists(REF_CLI),
                    reason="reference CLI binary not built")
def test_continue_training_from_reference_model(tmp_path):
    """init_model pointing at a model the REFERENCE binary trained: our
    engine must resume boosting from its scores and improve the metric
    (reference: boosting.cpp:35-69 LoadFileToBoosting + input_model)."""
    import subprocess
    conf = tmp_path / "t.conf"
    model = str(tmp_path / "ref5.txt")
    conf.write_text(
        "task = train\nobjective = binary\n"
        "data = /root/reference/examples/binary_classification/binary.train\n"
        "num_trees = 5\nnum_leaves = 31\nlearning_rate = 0.1\n"
        "min_data_in_leaf = 20\n"
        f"output_model = {model}\nverbosity = -1\n")
    r = subprocess.run([REF_CLI, f"config={conf}"], capture_output=True,
                       text=True, timeout=300, cwd=str(tmp_path))
    assert r.returncode == 0, r.stderr[-1000:]

    raw = np.loadtxt(
        "/root/reference/examples/binary_classification/binary.train")
    raw_t = np.loadtxt(
        "/root/reference/examples/binary_classification/binary.test")
    y, X = raw[:, 0], raw[:, 1:]
    p = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
         "min_data_in_leaf": 20, "metric": "auc", "verbose": -1}
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, 10, init_model=model)
    assert bst.num_trees() == 15  # 5 loaded + 10 new
    from sklearn.metrics import roc_auc_score
    auc5 = roc_auc_score(raw_t[:, 0],
                         lgb.Booster(model_file=model).predict(raw_t[:, 1:]))
    auc15 = roc_auc_score(raw_t[:, 0], bst.predict(raw_t[:, 1:]))
    assert auc15 > auc5 + 0.01, (auc5, auc15)
