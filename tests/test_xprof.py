"""Measured-roofline plane (obs/xprof.py, ISSUE 18): the stdlib trace
parser must survive garbage artifacts (explicit empty result, never a
crash), attribute device-op durations by lgbm/* scope, join the
analytic cost models into kernel_measured rows, and round-trip end to
end on a CPU capture; tpu_window.py triages unparseable captures;
trace_export.py and bench_history.py consume the same rows."""
import gzip
import json
import os
import sys
import types

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import xprof
from lightgbm_tpu.obs.report import load_events, validate_events

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _import_tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.remove(TOOLS)


def _fixture_doc():
    """A hand-built Chrome trace shaped like a jax.profiler export: one
    device track (pid 1, XLA-marked thread), one host python track
    (pid 2), scoped ops by name and by metadata args, an unscoped
    device op, and executor plumbing (``::``) that must never count as
    kernel work."""
    return {"traceEvents": [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": 1, "tid": 10, "name": "thread_name",
         "args": {"name": "XLA op profile"}},
        {"ph": "M", "pid": 2, "name": "process_name",
         "args": {"name": "python"}},
        {"ph": "M", "pid": 2, "tid": 20, "name": "thread_name",
         "args": {"name": "python"}},
        # scope in the op name itself
        {"ph": "X", "pid": 1, "tid": 10, "ts": 100.0, "dur": 400.0,
         "name": "lgbm/wave_hist/fusion.1"},
        # scope only in metadata args (the TPU named_scope path)
        {"ph": "X", "pid": 1, "tid": 10, "ts": 520.0, "dur": 80.0,
         "name": "fusion.2",
         "args": {"long_name": "lgbm/wave_partition/fusion.2"}},
        # unscoped device op -> the unattributed residual
        {"ph": "X", "pid": 1, "tid": 10, "ts": 620.0, "dur": 50.0,
         "name": "copy.3"},
        # infra plumbing: excluded from track busy/residual entirely
        {"ph": "X", "pid": 1, "tid": 10, "ts": 0.0, "dur": 1000.0,
         "name": "tensorflow::ThunkExecutor::Execute"},
        # host TraceAnnotation span (core.phase): spaced name verbatim
        {"ph": "X", "pid": 2, "tid": 20, "ts": 90.0, "dur": 700.0,
         "name": "lgbm/tree growth"},
        # host interpreter noise: no scope, not a device track
        {"ph": "X", "pid": 2, "tid": 20, "ts": 95.0, "dur": 5.0,
         "name": "numpy.ndarray.sum"},
    ]}


def _write_gz(path, doc):
    with gzip.open(path, "wb") as fh:
        fh.write(json.dumps(doc).encode())


_CTX = {"rows": 4096, "features": 12, "bins": 255, "leaves": 31,
        "iters": 2}


# ---------------------------------------------------------------------------
# parser robustness: garbage in, explicit empty out
# ---------------------------------------------------------------------------

def test_parse_empty_and_missing_dir(tmp_path):
    for p in (str(tmp_path), str(tmp_path / "nope"), ""):
        parsed = xprof.parse_trace_dir(p)
        assert parsed["files"] == 0 and parsed["parsed"] == 0
        assert parsed["ops"] == [] and parsed["errors"] == []
        attrib = xprof.attribute(parsed)
        assert attrib["kernels"] == {} and attrib["window_ms"] == 0.0
        assert xprof.measured_rooflines(attrib, _CTX) == []


def test_parse_corrupt_artifacts_explicit_empty(tmp_path):
    """Truncated gzip, non-gzip bytes, gzip-wrapped garbage json, a
    non-object root, and a missing traceEvents list all parse to the
    explicit empty result with one error entry each — no exception."""
    good = json.dumps(_fixture_doc()).encode()
    (tmp_path / "trunc.trace.json.gz").write_bytes(
        gzip.compress(good)[:-10])
    (tmp_path / "notgzip.trace.json.gz").write_bytes(b"this is not gzip")
    (tmp_path / "badjson.trace.json.gz").write_bytes(
        gzip.compress(b"{nope"))
    (tmp_path / "rootlist.trace.json").write_text("[1, 2]")
    (tmp_path / "noevents.trace.json").write_text('{"foo": 1}')
    parsed = xprof.parse_trace_dir(str(tmp_path))
    assert parsed["files"] == 5
    assert parsed["parsed"] == 0
    assert len(parsed["errors"]) == 5
    assert parsed["ops"] == [] and parsed["tracks"] == {}
    attrib = xprof.attribute(parsed)
    assert attrib["kernels"] == {}
    assert len(attrib["errors"]) == 5
    # one good artifact beside the garbage still attributes
    _write_gz(str(tmp_path / "ok.trace.json.gz"), _fixture_doc())
    parsed = xprof.parse_trace_dir(str(tmp_path))
    assert parsed["parsed"] == 1 and len(parsed["errors"]) == 5
    assert xprof.attribute(parsed)["kernels"]


# ---------------------------------------------------------------------------
# attribution + model join on the hand-built fixture
# ---------------------------------------------------------------------------

def test_fixture_attribution(tmp_path):
    _write_gz(str(tmp_path / "fix.trace.json.gz"), _fixture_doc())
    parsed = xprof.parse_trace_dir(str(tmp_path))
    assert parsed["parsed"] == 1 and not parsed["errors"]
    attrib = xprof.attribute(parsed)
    k = attrib["kernels"]
    assert k["lgbm/wave_hist"]["measured_ms"] == pytest.approx(0.4)
    assert k["lgbm/wave_hist"]["devices"] == ["/device:TPU:0"]
    # scope found in metadata args, not the op name
    assert k["lgbm/wave_partition"]["measured_ms"] == pytest.approx(0.08)
    # host annotation keeps its spaced phase name verbatim
    assert k["lgbm/tree growth"]["measured_ms"] == pytest.approx(0.7)
    assert k["lgbm/tree growth"]["devices"] == ["host"]
    dev = attrib["devices"]["/device:TPU:0"]
    # the :: infra op never counts; copy.3 is the only residual
    assert dev["ops"] == 3
    assert dev["busy_ms"] == pytest.approx(0.53)
    assert dev["unattributed_ms"] == pytest.approx(0.05)
    # window spans the earliest..latest X event (the infra op included)
    assert attrib["window_ms"] == pytest.approx(1.0)


def test_measured_rooflines_model_join(tmp_path):
    _write_gz(str(tmp_path / "fix.trace.json.gz"), _fixture_doc())
    attrib = xprof.attribute(xprof.parse_trace_dir(str(tmp_path)))
    rows = xprof.measured_rooflines(attrib, _CTX)
    byk = {r["kernel"]: r for r in rows}
    hist = byk["lgbm/wave_hist"]
    assert hist["source"] == "xprof" and hist["ops"] == 1
    assert hist["model"] == "wave_kernel" and hist["model_ms"] > 0
    assert hist["roofline_frac"] == pytest.approx(
        hist["model_ms"] / hist["measured_ms"], rel=1e-3)
    assert hist["bound"] in ("mxu", "hbm")
    part = byk["lgbm/wave_partition"]
    assert part["model"] == "partition" and part["model_ms"] > 0
    # the residual rides as its own per-device, measured-only row
    un = byk["unattributed"]
    assert un["measured_ms"] == pytest.approx(0.05)
    assert un["device"] == "/device:TPU:0"
    assert "model_ms" not in un
    assert un["occupancy"] == pytest.approx(0.05 / 1.0, rel=1e-3)


def test_record_measured_events_validate(tmp_path):
    """Emitted kernel_measured events pass the event schema and fold
    into the obs digest's xprof block."""
    sink = tmp_path / "telem"
    obs.reset()
    obs.enable(str(sink))
    try:
        _write_gz(str(tmp_path / "fix.trace.json.gz"), _fixture_doc())
        attrib = xprof.attribute(xprof.parse_trace_dir(str(tmp_path)))
        rows = xprof.record_measured(attrib, _CTX,
                                     trace_dir=str(tmp_path))
        digest = obs.digest()
        xp = digest["xprof"]
        assert xp["trace_parsed"] == 1
        assert xp["kernels"]["lgbm/wave_hist"]["roofline_frac"] > 0
    finally:
        obs.reset()
    events = load_events(str(sink))
    km = [e for e in events if e.get("event") == "kernel_measured"]
    assert len(km) == len(rows) and len(km) >= 4
    assert validate_events(events, kinds=("kernel_measured",)) == []


# ---------------------------------------------------------------------------
# arming + retrace attribution
# ---------------------------------------------------------------------------

def test_resolve_window_env_and_config(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_XPROF", raising=False)
    assert xprof.resolve_window(None) == 0
    cfg = types.SimpleNamespace(tpu_xprof=True, tpu_xprof_iters=4)
    assert xprof.resolve_window(cfg) == 4
    # a falsy env DISARMS even when config arms
    monkeypatch.setenv("LGBM_TPU_XPROF", "0")
    assert xprof.resolve_window(cfg) == 0
    monkeypatch.setenv("LGBM_TPU_XPROF", "off")
    assert xprof.resolve_window(cfg) == 0
    # truthy env arms with the config/default iters
    monkeypatch.setenv("LGBM_TPU_XPROF", "1")
    assert xprof.resolve_window(None) == 3
    assert xprof.resolve_window(cfg) == 4
    # a number > 1 sets the window directly
    monkeypatch.setenv("LGBM_TPU_XPROF", "7")
    assert xprof.resolve_window(None) == 7


def test_watch_jit_retrace_attribution(tmp_path, monkeypatch):
    """A signature change after the first call is a retrace: counted,
    and the compile event names the argument that forced it."""
    monkeypatch.setenv("LGBM_TPU_XPROF", "1")
    sink = tmp_path / "telem"
    obs.reset()
    obs.enable(str(sink))
    try:
        fn = xprof.watch_jit("lgbm/test_fn", lambda x: x)
        fn(np.zeros((4, 2)))
        fn(np.zeros((4, 2)))  # same signature: no retrace
        fn(np.zeros((8, 2)))  # shape change
        fn(np.zeros((8, 2), dtype=np.float32))  # dtype change
        assert xprof.compile_digest()["retraces"] == 2
    finally:
        obs.reset()
    re_ev = [e for e in load_events(str(sink))
             if e.get("event") == "compile" and e.get("kind") == "retrace"]
    assert len(re_ev) == 2
    assert all(e["jit"] == "lgbm/test_fn" for e in re_ev)
    assert any("arg0" in c for e in re_ev for c in e["changed"])
    assert validate_events(re_ev, kinds=("compile",)) == []


def test_watch_jit_identity_when_disarmed(monkeypatch):
    monkeypatch.delenv("LGBM_TPU_XPROF", raising=False)
    fn = lambda x: x  # noqa: E731
    assert xprof.watch_jit("lgbm/test_fn", fn) is fn
    assert xprof.watch_jit("lgbm/test_fn", None) is None


# ---------------------------------------------------------------------------
# end-to-end on CPU: capture -> parse -> attribute (slow: compile-heavy)
# ---------------------------------------------------------------------------

def test_e2e_capture_parse_attribute(tmp_path, monkeypatch):
    """LGBM_TPU_XPROF arms a mid-train capture window; after training
    the digest carries trace-attributed lgbm/* kernels with nonzero
    measured ms and the emitted events validate against the schemas."""
    monkeypatch.setenv("LGBM_TPU_XPROF", "2")
    monkeypatch.setenv("LGBM_TPU_XPROF_DIR", str(tmp_path / "cap"))
    sink = tmp_path / "telem"
    obs.reset()
    obs.enable(str(sink))
    try:
        rng = np.random.default_rng(3)
        X = rng.normal(size=(500, 10))
        y = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary", "num_leaves": 7,
                  "min_data_in_leaf": 5, "verbose": -1}
        ds = lgb.Dataset(X, label=y, params=params)
        lgb.train(params, ds, num_boost_round=5)
        digest = obs.digest()
        xp = digest.get("xprof") or {}
        assert xp.get("trace_parsed", 0) >= 1, xp
        assert not xp.get("errors")
        lgbm = {k: v for k, v in (xp.get("kernels") or {}).items()
                if k.startswith("lgbm/") and v.get("measured_ms", 0) > 0}
        assert lgbm, xp
    finally:
        obs.reset()
    events = load_events(str(sink))
    km = [e for e in events if e.get("event") == "kernel_measured"]
    assert km
    assert validate_events(
        events, kinds=("kernel_measured", "compile")) == []


# ---------------------------------------------------------------------------
# tpu_window: the trace leg parses its own capture
# ---------------------------------------------------------------------------

def _trace_leg_runner(write):
    """A canned runner for the trace leg: 'succeeds' (TRACE_OK, rc 0)
    after dropping whatever *write* leaves in the leg's trace dir —
    argv is [py, -c, code, rows, trace_dir]."""
    def run(argv, **kw):
        d = os.path.join(argv[-1], "plugins", "profile", "t1")
        os.makedirs(d, exist_ok=True)
        write(d)
        return types.SimpleNamespace(returncode=0, stdout="TRACE_OK\n",
                                     stderr="")
    return run


def test_tpu_window_unparseable_trace_triage(tmp_path):
    """A captured-but-unparseable trace becomes an unparseable-trace
    triage classification instead of silently passing trace_files > 0
    — even though the capture subprocess exited green."""
    tw = _import_tool("tpu_window")

    def write(d):
        with open(os.path.join(d, "host.trace.json.gz"), "wb") as fh:
            fh.write(b"definitely not a gzip stream")

    rec = tw.run_checklist(str(tmp_path), 3, dry_run=True,
                           runner=_trace_leg_runner(write),
                           backend="cpu (dry-run)", only={"trace"})
    assert rec["legs"]["trace"]["rc"] == 0
    assert rec["trace_files"] == 1
    assert rec["trace_parse"]["parsed"] == 0
    assert rec["trace_parse"]["errors"]
    assert rec["kernel_measured"] == []
    assert rec["legs"]["trace"]["trace_unparseable"] is True
    assert rec["triage"]["legs"]["trace"] == "unparseable-trace"
    assert "unparseable-trace" in rec["triage"]["classes"]
    # the classification round-trips through the artifact on disk
    payload = json.loads(
        (tmp_path / "BENCH_manual_r03.json").read_text())
    assert payload["triage"]["legs"]["trace"] == "unparseable-trace"


def test_tpu_window_embeds_measured_table(tmp_path):
    """A parseable capture embeds the per-kernel measured table into
    BENCH_manual_rN and trends through bench_history as
    kernel_measured/* — no triage block."""
    tw = _import_tool("tpu_window")

    def write(d):
        _write_gz(os.path.join(d, "host.trace.json.gz"), _fixture_doc())

    rec = tw.run_checklist(str(tmp_path), 4, dry_run=True,
                           runner=_trace_leg_runner(write),
                           backend="cpu (dry-run)", only={"trace"})
    assert rec["triage"] is None
    assert rec["trace_parse"]["parsed"] == 1
    assert rec["trace_parse"]["kernels_attributed"] >= 2
    kernels = {r["kernel"] for r in rec["kernel_measured"]}
    assert {"lgbm/wave_hist", "lgbm/wave_partition",
            "unattributed"} <= kernels
    bh = _import_tool("bench_history")
    rows = bh.collect([str(tmp_path / "BENCH_manual_r04.json")])
    assert rows[0].get("measured")
    assert any(k.startswith("kernel_measured/")
               for k in rows[0]["metrics"])


# ---------------------------------------------------------------------------
# trace_export: device-op summaries on their own Perfetto track
# ---------------------------------------------------------------------------

def test_trace_export_xprof_tracks_roundtrip():
    """kernel_measured + compile events render on their own ops/*
    tracks; an UNKNOWN kernel scope round-trips verbatim through the
    Chrome-trace document (json there and back) rather than being
    dropped or renamed."""
    te = _import_tool("trace_export")
    events = [
        {"event": "kernel_measured", "t": 100.0,
         "kernel": "lgbm/wave_hist", "ops": 3, "measured_ms": 4.0,
         "window_ms": 10.0, "source": "xprof",
         "device": "/device:TPU:0", "roofline_frac": 0.8,
         "bound": "hbm"},
        {"event": "kernel_measured", "t": 100.0,
         "kernel": "lgbm/some_future_kernel", "ops": 1,
         "measured_ms": 1.5, "window_ms": 10.0, "source": "xprof",
         "device": "/device:TPU:0"},
        {"event": "compile", "t": 101.0, "kind": "backend_compile",
         "jit": "lgbm/tree growth", "wall_s": 2.0},
        {"event": "compile", "t": 102.0, "kind": "cache_miss"},
    ]
    doc = json.loads(json.dumps(te.events_to_chrome(events)))
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    names = {e["name"] for e in xs}
    assert {"lgbm/wave_hist", "lgbm/some_future_kernel",
            "compile/backend_compile", "compile/cache_miss"} <= names
    tracks = {e["args"]["name"] for e in doc["traceEvents"]
              if e["ph"] == "M"}
    assert {"ops/xprof", "ops/compile"} <= tracks
    k = next(e for e in xs if e["name"] == "lgbm/wave_hist")
    assert k["dur"] == pytest.approx(4.0 * 1e3)  # ms -> us
    assert k["args"]["roofline_frac"] == 0.8
    assert k["args"]["synthesized"] is True
    unk = next(e for e in xs if e["name"] == "lgbm/some_future_kernel")
    assert unk["args"]["kernel"] == "lgbm/some_future_kernel"
    c = next(e for e in xs if e["name"] == "compile/backend_compile")
    assert c["dur"] == pytest.approx(2.0e6)  # wall_s -> us


# ---------------------------------------------------------------------------
# bench_history: trend + divergence gating
# ---------------------------------------------------------------------------

def test_bench_history_measured_divergence_flags():
    bh = _import_tool("bench_history")
    rows = [
        {"round": "r01", "context": ("a",),
         "metrics": {"kernel_measured/lgbm/wave_hist": 0.9}},
        {"round": "r02", "context": ("a",),
         "metrics": {"kernel_measured/lgbm/wave_hist": 0.4,
                     "kernel_measured/lgbm/wave_partition": 0.8,
                     "kernel_measured/lgbm/split_scan": 2.6}},
    ]
    flags = bh.find_measured_divergence(rows)
    assert {f["metric"] for f in flags} == {
        "kernel_measured/lgbm/wave_hist",
        "kernel_measured/lgbm/split_scan"}
    assert all(f["round"] == "r02" for f in flags)
    sides = {f["metric"]: f["side"] for f in flags}
    assert sides["kernel_measured/lgbm/wave_hist"] == "off-roofline"
    assert sides["kernel_measured/lgbm/split_scan"] == \
        "model-underprices"
    # canary rounds never gate: r01's clean fracs become latest
    rows[1]["canary"] = "cpu-forced"
    assert bh.find_measured_divergence(rows) == []


def test_bench_history_divergence_gates_exit(tmp_path, monkeypatch,
                                             capsys):
    """A > 2x measured-vs-model divergence fails --fail-on-regression
    exactly like a mode regression."""
    bh = _import_tool("bench_history")
    (tmp_path / "BENCH_r09.json").write_text(json.dumps({
        "metric": "train_throughput", "value": 100.0,
        "unit": "row_iters/s", "rows": 100, "iters": 3,
        "num_leaves": 31, "max_bin": 255,
        "kernel_measured": {"lgbm/wave_hist": 0.3}}))
    monkeypatch.setattr(sys, "argv",
                        ["bench_history.py", str(tmp_path),
                         "--fail-on-regression"])
    assert bh.main() == 1
    assert "MEASURED-VS-MODEL DIVERGENCE" in capsys.readouterr().out
    # the same round without the gate is informational only
    monkeypatch.setattr(sys, "argv",
                        ["bench_history.py", str(tmp_path)])
    assert bh.main() == 0
