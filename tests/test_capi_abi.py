"""Loadable C ABI: a real C program links liblgbm_tpu.so and trains.

SURVEY §2 row 52: the reference ships ``lib_lightgbm.so`` with ~65 C
exports (include/LightGBM/c_api.h).  Our full surface is Python-callable
(``capi.py``); this proves the CORE SUBSET is additionally a genuine C
ABI — compiled C code creates a dataset from raw row-major memory, sets
the label field, boosts, predicts, saves, and reloads the model, all
through ``LGBM_*`` symbols resolved by the dynamic linker (the compute
still runs on JAX via the embedded interpreter).
"""
import os
import subprocess
import sys
import sysconfig

import numpy as np
import pytest

from lightgbm_tpu import native

C_PROGRAM = r"""
#include <stdio.h>
#include <stdlib.h>
#include <stdint.h>
#include <math.h>

extern const char* LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromMat(const void*, int, int32_t, int32_t,
                                     int, const char*, const void*, void**);
extern int LGBM_DatasetSetField(void*, const char*, const void*, int, int);
extern int LGBM_DatasetGetNumData(void*, int32_t*);
extern int LGBM_DatasetGetNumFeature(void*, int32_t*);
extern int LGBM_DatasetFree(void*);
extern int LGBM_BoosterCreate(const void*, const char*, void**);
extern int LGBM_BoosterCreateFromModelfile(const char*, int32_t*, void**);
extern int LGBM_BoosterUpdateOneIter(void*, int*);
extern int LGBM_BoosterGetCurrentIteration(void*, int32_t*);
extern int LGBM_BoosterSaveModel(void*, int, int, const char*);
extern int LGBM_BoosterPredictForMat(void*, const void*, int, int32_t,
                                     int32_t, int, int, int, int,
                                     const char*, int64_t*, double*);
extern int LGBM_BoosterFree(void*);

#define CHECK(x) do { if ((x) != 0) { \
    fprintf(stderr, "FAIL %s: %s\n", #x, LGBM_GetLastError()); \
    return 1; } } while (0)

int main(int argc, char **argv) {
    const int N = 400, F = 4;
    double *X = malloc(sizeof(double) * N * F);
    float *y = malloc(sizeof(float) * N);
    unsigned s = 42;
    for (int i = 0; i < N; ++i) {
        for (int j = 0; j < F; ++j) {
            s = s * 1664525u + 1013904223u;
            X[i * F + j] = ((double)(s >> 8) / 16777216.0) * 4.0 - 2.0;
        }
        y[i] = (X[i * F] + 0.3 * X[i * F + 1] > 0.0) ? 1.0f : 0.0f;
    }
    void *ds = NULL, *bst = NULL;
    const char *p = "objective=binary num_leaves=7 min_data_in_leaf=5 "
                    "verbose=-1";
    CHECK(LGBM_DatasetCreateFromMat(X, 1, N, F, 1, p, NULL, &ds));
    CHECK(LGBM_DatasetSetField(ds, "label", y, N, 0));
    int32_t nd = 0, nf = 0;
    CHECK(LGBM_DatasetGetNumData(ds, &nd));
    CHECK(LGBM_DatasetGetNumFeature(ds, &nf));
    if (nd != N || nf != F) { fprintf(stderr, "dims %d %d\n", nd, nf); return 2; }
    CHECK(LGBM_BoosterCreate(ds, p, &bst));
    for (int it = 0; it < 10; ++it) {
        int fin = 0;
        CHECK(LGBM_BoosterUpdateOneIter(bst, &fin));
    }
    int32_t cur = 0;
    CHECK(LGBM_BoosterGetCurrentIteration(bst, &cur));
    if (cur != 10) { fprintf(stderr, "iters %d\n", cur); return 3; }
    int64_t out_len = 0;
    double *pred = malloc(sizeof(double) * N);
    CHECK(LGBM_BoosterPredictForMat(bst, X, 1, N, F, 1, 0, 0, -1, "",
                                    &out_len, pred));
    if (out_len != N) { fprintf(stderr, "len %lld\n", (long long)out_len); return 4; }
    /* separation check: mean pred of positives > negatives + margin */
    double sp = 0, sn = 0; int np_ = 0, nn = 0;
    for (int i = 0; i < N; ++i) {
        if (y[i] > 0.5) { sp += pred[i]; ++np_; } else { sn += pred[i]; ++nn; }
    }
    if (!(sp / np_ > sn / nn + 0.2)) {
        fprintf(stderr, "no separation %f %f\n", sp / np_, sn / nn);
        return 5;
    }
    CHECK(LGBM_BoosterSaveModel(bst, 0, -1, argv[1]));
    int32_t iters2 = 0;
    void *bst2 = NULL;
    CHECK(LGBM_BoosterCreateFromModelfile(argv[1], &iters2, &bst2));
    double *pred2 = malloc(sizeof(double) * N);
    CHECK(LGBM_BoosterPredictForMat(bst2, X, 1, N, F, 1, 0, 0, -1, "",
                                    &out_len, pred2));
    for (int i = 0; i < N; ++i) {
        if (fabs(pred[i] - pred2[i]) > 1e-10) {
            fprintf(stderr, "roundtrip mismatch @%d\n", i);
            return 6;
        }
    }
    CHECK(LGBM_BoosterFree(bst2));
    CHECK(LGBM_BoosterFree(bst));
    CHECK(LGBM_DatasetFree(ds));
    printf("C_ABI_OK iters=%d\n", cur);
    return 0;
}
"""


def test_c_program_trains_through_the_abi(tmp_path):
    lib = native.capi_abi_lib()
    if lib is None:
        pytest.skip("C toolchain or libpython unavailable")
    src = tmp_path / "main.c"
    src.write_text(C_PROGRAM)
    exe = str(tmp_path / "abi_demo")
    libdir = os.path.dirname(lib)
    r = subprocess.run(
        ["gcc", "-O1", str(src), f"-L{libdir}",
         f"-l:{os.path.basename(lib)}", f"-Wl,-rpath,{libdir}", "-lm",
         "-o", exe], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr

    site = sysconfig.get_paths()["purelib"]
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [repo, site] + env.get("PYTHONPATH", "").split(os.pathsep))
    env["JAX_PLATFORMS"] = "cpu"
    model = str(tmp_path / "abi_model.txt")
    r = subprocess.run([exe, model], capture_output=True, text=True,
                       env=env, timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "C_ABI_OK iters=10" in r.stdout

    # the C-trained model is a normal reference-format model file: the
    # Python API loads it straight back
    import lightgbm_tpu as lgb
    bst = lgb.Booster(model_file=model)
    assert bst.current_iteration() == 10
