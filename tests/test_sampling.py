"""Balanced (pos/neg) bagging and by-node feature sampling
(reference: gbdt.cpp:160-276 balanced bagging; col_sampler.hpp GetByNode)."""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=900, seed=2):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.5 * X[:, 1] + rng.logistic(size=n) * 0.3 > 0.8)
    return X, y.astype(np.float64)


BASE = {"objective": "binary", "num_leaves": 15, "verbose": -1,
        "min_data_in_leaf": 5}


def test_balanced_bagging_mask_respects_class_fractions():
    X, y = _data()
    p = dict(BASE, pos_bagging_fraction=0.2, neg_bagging_fraction=0.9,
             bagging_freq=1)
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, num_boost_round=3)
    mask = bst._gbdt._bag_mask_host
    pos, neg = y == 1, y == 0
    assert mask[pos].sum() == int(0.2 * pos.sum())
    assert mask[neg].sum() == int(0.9 * neg.sum())
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.7


def test_balanced_bagging_requires_binary_labels():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    y = rng.normal(size=300)  # regression labels
    p = {"objective": "regression", "verbose": -1, "num_leaves": 7,
         "min_data_in_leaf": 5, "pos_bagging_fraction": 0.5,
         "bagging_freq": 1}
    ds = lgb.Dataset(X, label=y, params=p)
    with pytest.raises(lgb.LightGBMError, match="binary"):
        lgb.train(p, ds, num_boost_round=2)


def test_feature_fraction_bynode_varies_within_tree():
    X, y = _data()
    # one feature per node: a single tree must still mix features, which
    # per-TREE sampling (feature_fraction) cannot do at this fraction
    p = dict(BASE, feature_fraction_bynode=1.0 / 6, num_leaves=31)
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, num_boost_round=2)
    tree0 = bst.dump_model()["tree_info"][0]["tree_structure"]
    feats = set()

    def walk(node):
        if "split_feature" in node:
            feats.add(node["split_feature"])
            walk(node["left_child"])
            walk(node["right_child"])

    walk(tree0)
    assert len(feats) > 1
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.6


def test_feature_fraction_bynode_deterministic():
    X, y = _data()
    p = dict(BASE, feature_fraction_bynode=0.5)

    def run():
        ds = lgb.Dataset(X, label=y, params=p)
        return lgb.train(p, ds, num_boost_round=3).predict(X)

    np.testing.assert_array_equal(run(), run())
