"""EFB (Exclusive Feature Bundling) tests.

Mirrors the reference's EFB behavior (reference: src/io/dataset.cpp:41-263):
mutually-exclusive sparse features share physical columns, training results
are unchanged, and conflict budgets are honored.
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset


def _onehotish(n=3000, blocks=40, seed=0):
    """Sparse mutually-exclusive features: one-hot blocks + 2 dense cols."""
    rng = np.random.default_rng(seed)
    sel = rng.integers(0, blocks, size=n)
    Xs = np.zeros((n, blocks))
    Xs[np.arange(n), sel] = rng.random(n) + 0.5
    Xd = rng.normal(size=(n, 2))
    X = np.hstack([Xd, Xs])
    y = (Xd[:, 0] + (sel < blocks // 2) + rng.logistic(size=n) * 0.3 > 0.5)
    return X, y.astype(np.float64)


def test_bundles_reduce_physical_columns():
    X, y = _onehotish()
    cfg = Config.from_params({"verbose": -1})
    ds = BinnedDataset.from_matrix(X, cfg)
    assert ds.bundle is not None
    assert ds.num_phys_features < ds.num_features
    # the 40 exclusive one-hot columns collapse into very few bundles
    assert ds.num_phys_features <= 2 + 6
    assert ds.num_features == X.shape[1]
    # physical bins stay within uint8
    assert ds.X_bin.dtype == np.uint8
    assert int(ds.phys_max_bins().max()) <= 256


def test_bundle_decode_roundtrip():
    """Physical encode/decode returns each feature's own bin, except the
    default bin (reconstructed via FixHistogram semantics)."""
    X, y = _onehotish(n=800, blocks=10)
    cfg = Config.from_params({"verbose": -1})
    ds = BinnedDataset.from_matrix(X, cfg)
    assert ds.bundle is not None
    b = ds.bundle
    used = ds.real_feature_idx
    for inner in range(ds.num_features):
        m = ds.bin_mappers[int(used[inner])]
        fb = np.asarray(m.value_to_bin(X[:, int(used[inner])]))
        colp = ds.X_bin[:, b.feat2phys[inner]].astype(np.int64)
        off, nb = int(b.feat_offset[inner]), m.num_bin
        inr = (colp >= off) & (colp < off + nb) if off else np.ones_like(colp, bool)
        dec = np.where(inr, colp - off, m.default_bin)
        if off == 0:  # singleton column: exact
            np.testing.assert_array_equal(dec, fb)
        else:
            nz = fb != m.default_bin
            # non-default values survive unless lost to a conflict
            agree = dec[nz] == fb[nz]
            assert agree.mean() > 0.95
            # default rows always decode to default
            np.testing.assert_array_equal(dec[~nz], m.default_bin)


def test_training_metrics_unchanged_vs_no_bundle():
    X, y = _onehotish()
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5, "metric": "auc"}
    out = {}
    for enable in (True, False):
        p = dict(params, enable_bundle=enable)
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=15)
        pred = bst.predict(X)
        from sklearn.metrics import roc_auc_score
        out[enable] = roc_auc_score(y, pred)
    assert out[True] > 0.80
    # EFB is an approximation only on conflict rows; exclusive features
    # have none, so quality must match closely
    assert abs(out[True] - out[False]) < 0.01


def test_bundled_predict_device_matches_host():
    X, y = _onehotish(n=2000, blocks=20, seed=3)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=params)
    bst = lgb.train(params, ds, num_boost_round=10)
    g = bst._gbdt
    assert g.train_ds.bundle is not None
    Xt, _ = _onehotish(n=700, blocks=20, seed=9)
    start, stop = g._iter_window(None, 0)
    host = np.zeros((Xt.shape[0], 1))
    for it in range(start, stop):
        host[:, 0] += g.models[it].predict(Xt)
    dev = g._predict_raw_device(Xt, start, stop)
    np.testing.assert_allclose(dev, host, rtol=0, atol=1e-4)


def test_bundle_dataset_io_roundtrip(tmp_path):
    from lightgbm_tpu.io.dataset_io import load_dataset, save_dataset
    X, y = _onehotish(n=500, blocks=8)
    cfg = Config.from_params({"verbose": -1})
    ds = BinnedDataset.from_matrix(X, cfg)
    ds.metadata.set_label(y)
    assert ds.bundle is not None
    path = str(tmp_path / "ds.npz")
    save_dataset(ds, path)
    ds2 = load_dataset(path)
    assert ds2.bundle is not None
    np.testing.assert_array_equal(ds2.bundle.feat2phys, ds.bundle.feat2phys)
    np.testing.assert_array_equal(ds2.X_bin, ds.X_bin)
    assert ds2.num_features == ds.num_features


def test_enable_bundle_false_is_identity():
    X, y = _onehotish(n=500, blocks=8)
    cfg = Config.from_params({"verbose": -1, "enable_bundle": False})
    ds = BinnedDataset.from_matrix(X, cfg)
    assert ds.bundle is None
    assert ds.num_phys_features == ds.num_features


def test_wave_grower_bundled_matches_serial():
    """The Pallas wave path's bundle expansion == the XLA serial grower
    (interpret mode; the analog of GPU_DEBUG_COMPARE,
    gpu_tree_learner.cpp:1011-1043)."""
    import jax
    import jax.numpy as jnp
    from lightgbm_tpu.core.grower import make_grower
    from lightgbm_tpu.core.meta import (SplitConfig, build_device_meta,
                                        padded_phys_width)
    from lightgbm_tpu.core.wave_grower import build_wave_grow_fn

    X, y = _onehotish(n=1200, blocks=12, seed=5)
    params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    h = ds._handle
    assert h.bundle is not None
    cfg = Config.from_params(params)
    meta, B = build_device_meta(h, cfg)
    B_phys = padded_phys_width(h)
    scfg = SplitConfig.from_config(cfg)
    rng = np.random.default_rng(0)
    g = jnp.asarray(rng.normal(size=h.num_data).astype(np.float32))
    hs = jnp.asarray((rng.random(h.num_data) * 0.25 + 0.1).astype(np.float32))
    mask = jnp.ones(h.num_data, jnp.float32)
    fmask = jnp.ones(h.num_features, bool)

    grow_s = make_grower(meta, scfg, B, B_phys=B_phys, bundled=True)
    tr_s, lid_s = grow_s(jnp.asarray(h.X_bin), g, hs, mask, fmask)

    binsT = jnp.asarray(np.ascontiguousarray(h.X_bin.T))
    grow_w = jax.jit(build_wave_grow_fn(
        meta, scfg, B, wave_capacity=1, highest=True, interpret=True,
        B_phys=B_phys, bundled=True))
    tr_w, lid_w = grow_w(binsT, g, hs, mask, fmask)

    assert int(tr_w.num_leaves) == int(tr_s.num_leaves)
    np.testing.assert_array_equal(np.asarray(tr_w.split_feature),
                                  np.asarray(tr_s.split_feature))
    np.testing.assert_array_equal(np.asarray(tr_w.threshold_bin),
                                  np.asarray(tr_s.threshold_bin))
    np.testing.assert_allclose(np.asarray(tr_w.leaf_value),
                               np.asarray(tr_s.leaf_value), atol=1e-4)
    np.testing.assert_array_equal(np.asarray(lid_w), np.asarray(lid_s))


def test_bundled_dataset_with_parallel_learner():
    """A dataset bundled at construction (serial-default params) must train
    correctly when the BOOSTER params later select a parallel learner —
    the mesh growers expand physical histograms like the serial path."""
    X, y = _onehotish(n=2048, blocks=20, seed=5)
    ds_params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                 "min_data_in_leaf": 5}
    preds = {}
    for tl in ("serial", "data"):
        ds = lgb.Dataset(X, label=y, params=ds_params)
        ds.construct()
        assert ds._handle.bundle is not None  # bundling actually happened
        p = dict(ds_params, tree_learner=tl)
        bst = lgb.train(p, ds, num_boost_round=5)
        preds[tl] = bst.predict(X)
    np.testing.assert_allclose(preds["data"], preds["serial"], atol=1e-5)


def test_bundled_dataset_feature_parallel_rejected():
    X, y = _onehotish(n=1024, blocks=20, seed=6)
    ds = lgb.Dataset(X, label=y, params={"verbose": -1})
    ds.construct()
    assert ds._handle.bundle is not None
    p = {"objective": "binary", "num_leaves": 7, "verbose": -1,
         "tree_learner": "feature", "min_data_in_leaf": 5}
    with pytest.raises(Exception, match="bundle"):
        lgb.train(p, ds, num_boost_round=2)


def test_bundled_dataset_voting_parallel_full_vote_matches_data():
    """EFB + voting (refused pre-r5; reference packs group histograms for
    any bundling, voting_parallel_tree_learner.cpp:203-259): with top_k
    >= F_phys every physical column survives the gate, so the result
    equals data-parallel exactly."""
    X, y = _onehotish(n=2048, blocks=20, seed=7)
    ds_params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                 "min_data_in_leaf": 5}
    preds = {}
    for tl in ("data", "voting"):
        ds = lgb.Dataset(X, label=y, params=ds_params)
        ds.construct()
        assert ds._handle.bundle is not None
        p = dict(ds_params, tree_learner=tl, top_k=64)
        bst = lgb.train(p, ds, num_boost_round=5)
        preds[tl] = bst.predict(X)
    np.testing.assert_allclose(preds["voting"], preds["data"], atol=1e-6)


def test_bundled_voting_tight_gate_no_phantom_splits():
    """A tight top_k gates physical columns OFF some passes; their members
    must scan all-zero histograms (skipped default-bin fix), never
    fabricated leaf mass.  Loss must stay sane and every chosen split
    must carry real gain."""
    X, y = _onehotish(n=2048, blocks=20, seed=8)
    ds_params = {"objective": "binary", "num_leaves": 15, "verbose": -1,
                 "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=ds_params)
    ds.construct()  # serial-default params -> bundling happens
    assert ds._handle.bundle is not None
    p = dict(ds_params, tree_learner="voting", top_k=2)
    bst = lgb.train(p, ds, num_boost_round=8)
    pred = bst.predict(X)
    eps = 1e-15
    ll = -np.mean(y * np.log(np.clip(pred, eps, 1))
                  + (1 - y) * np.log(np.clip(1 - pred, eps, 1)))
    assert ll < 0.60, ll  # learns despite the gate; base rate ~0.69
    dump = bst.dump_model()
    def gains(node, out):
        if "split_gain" in node:
            out.append(node["split_gain"])
            gains(node["left_child"], out)
            gains(node["right_child"], out)
    allg = []
    for t in dump["tree_info"]:
        gains(t["tree_structure"], allg)
    assert allg and all(g > 0 for g in allg)


def test_reference_cli_efb_auc_parity():
    """Reference-CLI oracle on bundled sparse data: the reference binary
    (enable_bundle=true, 15 trees, num_leaves=15, lr=0.1,
    min_data_in_leaf=20) reaches valid AUC 0.91748 on
    tests/fixtures/sparse.{train,test}; our EFB path must land within
    0.01 while actually bundling."""
    import os
    fix = os.path.join(os.path.dirname(__file__), "fixtures")
    tr = np.loadtxt(os.path.join(fix, "sparse.train"))
    te = np.loadtxt(os.path.join(fix, "sparse.test"))
    p = {"objective": "binary", "metric": "auc", "num_leaves": 15,
         "learning_rate": 0.1, "min_data_in_leaf": 20,
         "enable_bundle": True, "verbose": -1}
    ds = lgb.Dataset(tr[:, 1:], label=tr[:, 0], params=p)
    dv = lgb.Dataset(te[:, 1:], label=te[:, 0], reference=ds)
    res = {}
    bst = lgb.train(p, ds, 15, valid_sets=[dv], valid_names=["valid"],
                    callbacks=[lgb.record_evaluation(res)])
    assert ds._handle.bundle is not None  # EFB actually engaged
    assert ds._handle.X_bin.shape[1] < 33
    got = res["valid"]["auc"][-1]
    assert abs(got - 0.91748) < 0.01, got
