"""Multi-host bootstrap plumbing (reference: network.cpp Network::Init,
config.h network parameters). Actual multi-process bring-up needs real
hosts; these cover the config surface and single-host no-op guarantees."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import distributed, mesh


def test_parse_machine_list_forms(tmp_path):
    got = distributed.parse_machine_list("10.0.0.1:121,10.0.0.2:122")
    assert got == ["10.0.0.1:121", "10.0.0.2:122"]
    # missing ports get the default
    got = distributed.parse_machine_list("hostA,hostB", default_port=9000)
    assert got == ["hostA:9000", "hostB:9000"]
    # file form, one "ip port" per line like the reference's mlist
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 121\n10.0.0.2 122\n")
    got = distributed.parse_machine_list(machine_list_filename=str(p))
    assert got == ["10.0.0.1:121", "10.0.0.2:122"]


def test_single_machine_is_noop():
    assert distributed.init_distributed(num_machines=1) is False
    cfg = lgb.Config.from_params({"verbose": -1})
    assert distributed.init_distributed(cfg) is False


def test_machine_count_mismatch_is_fatal():
    with pytest.raises(lgb.LightGBMError, match="machine list"):
        distributed.init_distributed(machines="a:1,b:2,c:3", num_machines=2)


def test_missing_machine_list_file_is_fatal(tmp_path):
    with pytest.raises(lgb.LightGBMError, match="does not exist"):
        distributed.parse_machine_list(
            machine_list_filename=str(tmp_path / "nope.txt"))


def test_set_network_records_topology():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 1)
    try:
        bst.set_network(["10.1.1.1:121", "10.1.1.2:121"], num_machines=2)
        assert mesh.NETWORK["num_machines"] == 2
        assert mesh.NETWORK["machines"] == "10.1.1.1:121,10.1.1.2:121"
        bst.free_network()
    finally:
        mesh.NETWORK.update(machines="", num_machines=1, rank=0)


def test_process_id_resolution(monkeypatch):
    monkeypatch.setitem(mesh.NETWORK, "rank", 0)
    monkeypatch.setenv("LGBM_TPU_RANK", "3")
    assert distributed.process_id() == 3
    monkeypatch.setitem(mesh.NETWORK, "rank", 1)
    assert distributed.process_id() == 1


def test_process_id_from_machine_list(monkeypatch):
    monkeypatch.setitem(mesh.NETWORK, "rank", 0)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    monkeypatch.delenv("LGBM_TPU_RANK", raising=False)
    # local host appears second -> rank 1 (reference: Network::Init finds
    # the local machine in the list)
    assert distributed.process_id(["10.9.9.9:12400", "localhost:12400"]) == 1
    # unknown everywhere -> None, deferring to jax cluster auto-detection
    assert distributed.process_id(["10.9.9.8:1", "10.9.9.9:1"]) is None


def test_global_bin_sample_single_host_identity():
    s = np.random.default_rng(0).normal(size=(50, 3))
    out, n_global = distributed.global_bin_sample(s, 200)
    assert out is s  # no-op outside an initialized multi-host runtime
    assert n_global == 200
