"""Multi-host bootstrap plumbing (reference: network.cpp Network::Init,
config.h network parameters). Actual multi-process bring-up needs real
hosts; these cover the config surface and single-host no-op guarantees."""
import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.parallel import distributed, mesh


def test_parse_machine_list_forms(tmp_path):
    got = distributed.parse_machine_list("10.0.0.1:121,10.0.0.2:122")
    assert got == ["10.0.0.1:121", "10.0.0.2:122"]
    # missing ports get the default
    got = distributed.parse_machine_list("hostA,hostB", default_port=9000)
    assert got == ["hostA:9000", "hostB:9000"]
    # file form, one "ip port" per line like the reference's mlist
    p = tmp_path / "mlist.txt"
    p.write_text("10.0.0.1 121\n10.0.0.2 122\n")
    got = distributed.parse_machine_list(machine_list_filename=str(p))
    assert got == ["10.0.0.1:121", "10.0.0.2:122"]


def test_single_machine_is_noop():
    assert distributed.init_distributed(num_machines=1) is False
    cfg = lgb.Config.from_params({"verbose": -1})
    assert distributed.init_distributed(cfg) is False


def test_machine_count_mismatch_is_fatal():
    with pytest.raises(lgb.LightGBMError, match="machine list"):
        distributed.init_distributed(machines="a:1,b:2,c:3", num_machines=2)


def test_missing_machine_list_file_is_fatal(tmp_path):
    with pytest.raises(lgb.LightGBMError, match="does not exist"):
        distributed.parse_machine_list(
            machine_list_filename=str(tmp_path / "nope.txt"))


def test_set_network_records_topology():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4))
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 1)
    try:
        bst.set_network(["10.1.1.1:121", "10.1.1.2:121"], num_machines=2)
        assert mesh.NETWORK["num_machines"] == 2
        assert mesh.NETWORK["machines"] == "10.1.1.1:121,10.1.1.2:121"
        bst.free_network()
    finally:
        mesh.NETWORK.update(machines="", num_machines=1, rank=0)


def test_process_id_resolution(monkeypatch):
    monkeypatch.setitem(mesh.NETWORK, "rank", 0)
    monkeypatch.setenv("LGBM_TPU_RANK", "3")
    assert distributed.process_id() == 3
    monkeypatch.setitem(mesh.NETWORK, "rank", 1)
    assert distributed.process_id() == 1


def test_process_id_from_machine_list(monkeypatch):
    monkeypatch.setitem(mesh.NETWORK, "rank", 0)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    monkeypatch.delenv("LGBM_TPU_RANK", raising=False)
    # local host appears second -> rank 1 (reference: Network::Init finds
    # the local machine in the list)
    assert distributed.process_id(["10.9.9.9:12400", "localhost:12400"]) == 1
    # unknown everywhere -> None, deferring to jax cluster auto-detection
    assert distributed.process_id(["10.9.9.8:1", "10.9.9.9:1"]) is None


def test_jax_private_distributed_api_contract():
    """FAIL LOUDLY the day jax moves jax._src.distributed.global_state.

    parallel/distributed.py jax_distributed_state() is the single access
    point for this PRIVATE attribute (consumed by _runtime_active and
    obs/core.py _process_index) to detect an active multi-host runtime
    WITHOUT initializing a backend — the public probes can hang ~30 min
    on a wedged accelerator lease.
    pyproject.toml pins jax to the vetted range (jax>=0.4.26,<0.6).  If
    this test fails: jax moved the API — update jax_distributed_state's
    import, audit the two call sites' fallbacks, and re-vet the pin.
    """
    from jax._src.distributed import global_state  # the contract itself
    assert hasattr(global_state, "client"), \
        "global_state lost its .client attribute — update " \
        "parallel/distributed.py jax_distributed_state and obs/core.py"
    state = distributed.jax_distributed_state()
    assert state is not None, \
        "jax_distributed_state() declined an import that works — " \
        "its guard is broken"
    assert state.client is None  # no runtime was brought up in this suite
    # and the guarded consumer still answers without touching a backend
    assert distributed._runtime_active() is False


def test_global_bin_sample_single_host_identity():
    s = np.random.default_rng(0).normal(size=(50, 3))
    out, n_global = distributed.global_bin_sample(s, 200)
    assert out is s  # no-op outside an initialized multi-host runtime
    assert n_global == 200


def test_two_process_data_parallel_bitmatch(tmp_path):
    """REAL 2-process bring-up on the CPU backend: spawn two ranks with a
    local coordinator, run init_distributed + global_bin_sample + 5 rounds
    of data-parallel boosting (histogram psum ACROSS processes), and
    assert both ranks produced identical trees that bit-match the serial
    single-process oracle.  Closes the gap the reference never closed in
    CI (docs/Parallel-Learning-Guide.rst:55-100 is manual-run only)."""
    import json
    import os
    import socket
    import subprocess
    import sys

    # one free port for the coordinator (hosts[0]); the machine list's
    # second entry is address-only metadata — nothing binds it
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    base_port = s.getsockname()[1]
    s.close()

    worker = os.path.join(os.path.dirname(__file__), "dist_worker.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # 1 CPU device per process -> 2-device mesh
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(worker)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    outs = [str(tmp_path / f"rank{r}.json") for r in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, worker, str(r), str(base_port), outs[r]],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for r in range(2)]
    logs = []
    for pr in procs:
        try:
            # generous: the pass/fail signal is the fingerprint match, not
            # wall-clock — the 1-CPU container is compile-bound and two
            # concurrent ranks compile everything twice
            out, _ = pr.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            pytest.fail("2-process worker timed out; partial output:\n"
                        + "\n".join(logs))
        logs.append(out)
    assert all(pr.returncode == 0 for pr in procs), "\n".join(logs)

    res = [json.load(open(o)) for o in outs]
    assert all(r["ok"] for r in res)
    if any(r.get("skipped") for r in res):
        # the workers probed the runtime and found the backend cannot move
        # data through cross-process device collectives (fleet/launch.py
        # device_collective_support) — an environment gap, not a product
        # failure; the host-TCP fleet transport covers this path in CI
        pytest.skip(res[0].get("reason") or res[1].get("reason")
                    or "cross-process device collectives unsupported")
    assert all(r["global_devices"] == 2 for r in res)
    assert all(r["pooled_rows"] == 512 for r in res)
    # sparse sample pooling: both ranks pooled to the same matrix AND
    # derived IDENTICAL bin mappers from their different half-samples
    assert res[0]["pooled_sparse_nnz"] == res[1]["pooled_sparse_nnz"] > 0
    assert res[0]["sparse_bin_offsets"] == res[1]["sparse_bin_offsets"]
    assert res[0]["sparse_bounds_fp"] == res[1]["sparse_bounds_fp"]
    # ...and they match a SINGLE-HOST oracle built from the full matrix
    # (catches symmetric pooling bugs both ranks would share)
    import scipy.sparse as sp
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import BinnedDataset
    rng = np.random.default_rng(0)
    X = rng.normal(size=(512, 5))
    Xs = X.copy()
    Xs[Xs < 0.5] = 0.0
    Xp = np.concatenate([Xs[0::2], Xs[1::2]])  # pooled host order
    oracle = BinnedDataset.from_sample(
        sp.csc_matrix(Xp), 512, Config.from_params(
            {"verbose": -1, "max_bin": 31}))
    assert res[0]["sparse_bin_offsets"] == np.asarray(
        oracle.bin_offsets).tolist()
    fp = [round(float(np.asarray(m.bin_upper_bound)[:-1].sum()), 9)
          for m in oracle.bin_mappers]
    assert res[0]["sparse_bounds_fp"] == fp
    # pre-sharded streaming ingestion (ingest/, ISSUE 14): both ranks —
    # each streaming ONLY its contiguous half — derived IDENTICAL bin
    # mappers via the real-collective sample pooling...
    assert res[0]["ingest_bin_offsets"] == res[1]["ingest_bin_offsets"]
    assert res[0]["ingest_bounds_fp"] == res[1]["ingest_bounds_fp"]
    # ...matching the single-host oracle built from the full matrix,
    # and their locally-binned halves concatenate to the oracle's
    # bin matrix bit-exactly
    import hashlib
    from lightgbm_tpu.ingest import ArraySource, ingest_dataset
    icfg = Config.from_params({"verbose": -1, "max_bin": 31})
    ing_oracle = ingest_dataset(
        ArraySource(X, label=(X[:, 0] + X[:, 1] * X[:, 2] > 0)
                    .astype(np.float64), chunk_rows=100), icfg)
    assert res[0]["ingest_bin_offsets"] == np.asarray(
        ing_oracle.bin_offsets).tolist()
    fp = [round(float(np.nansum(np.asarray(m.bin_upper_bound)[:-1])), 9)
          for m in ing_oracle.bin_mappers]
    assert res[0]["ingest_bounds_fp"] == fp
    assert res[0]["ingest_xbin_sha"] == hashlib.sha256(
        np.ascontiguousarray(ing_oracle.X_bin[:256]).tobytes()).hexdigest()
    assert res[1]["ingest_xbin_sha"] == hashlib.sha256(
        np.ascontiguousarray(ing_oracle.X_bin[256:]).tobytes()).hexdigest()
    # both ranks saw identical data-parallel trees (replicated outputs)
    assert res[0]["dp_trees"] == res[1]["dp_trees"]
    # the cross-process psum'd training matches the serial oracle:
    # structure bit-exact, leaf values up to f32 psum reduction order
    # (the same tolerance mesh.py documents for single-process psum)
    for dp, sr in zip(res[0]["dp_trees"], res[0]["serial_trees"]):
        assert dp["num_leaves"] == sr["num_leaves"]
        assert dp["split_feature"] == sr["split_feature"]
        assert dp["threshold_bin"] == sr["threshold_bin"]
        np.testing.assert_allclose(dp["leaf_value"], sr["leaf_value"],
                                   rtol=1e-5, atol=1e-7)
    # the health divergence audit over the REAL cross-process gather:
    # identical replicated state passed, and after rank 1 corrupted its
    # score copy every rank caught the mismatch (obs/health.py)
    assert all(r["divergence_caught"] for r in res)
