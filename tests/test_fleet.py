"""Serving fleet tests: registry hot-swap/rollback, replica router,
circuit breakers, priority shedding (ISSUE 10).

The contracts under test:

- a canary-gated swap is ATOMIC and request-loss-free under concurrent
  mixed /predict + /explain traffic, with every response attributable
  to exactly one model version (version echoed, predictions bit-match
  that version's model);
- a canary rejection leaves the old version serving, untouched;
- rollback (manual and automatic post-swap) restores the resident
  previous version instantly;
- one wedged replica of a routed pair degrades capacity, not
  availability (breaker opens, half-open probe recovers);
- overload sheds low-priority requests first, with per-class counters.
"""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.robust import faults
from lightgbm_tpu.robust.watchdog import CircuitBreaker
from lightgbm_tpu.serve import (ModelRegistry, PredictorSession,
                                PredictServer, ReplicaRouter,
                                ServeOverloadError, SwapRejected)

P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
     "verbose": -1}


@pytest.fixture(autouse=True)
def _disarm():
    faults.disarm()
    yield
    faults.disarm()


@pytest.fixture(scope="module")
def fleet_models(tmp_path_factory):
    """Two small models over the same feature space whose predictions
    differ, saved to files, plus the probe matrix."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 6))
    X[rng.random(X.shape) < 0.04] = np.nan
    y = (np.nan_to_num(X[:, 0]) - 0.4 * np.nan_to_num(X[:, 2]) > 0
         ).astype(np.float64)
    b1 = lgb.train(P, lgb.Dataset(X, label=y, params=P),
                   num_boost_round=4)
    P2 = dict(P, num_leaves=5, learning_rate=0.2)
    b2 = lgb.train(P2, lgb.Dataset(X, label=y, params=P2),
                   num_boost_round=6)
    d = tmp_path_factory.mktemp("fleet_models")
    m1, m2 = str(d / "m1.txt"), str(d / "m2.txt")
    b1.save_model(m1)
    b2.save_model(m2)
    return m1, b1, m2, b2, X


def _cfg(**over):
    base = dict(P, tpu_serve_max_batch=64, tpu_serve_max_wait_ms=1.0,
                tpu_serve_canary_rows=16, tpu_serve_canary_probes=2,
                tpu_serve_rollback_watch_s=0.0, tpu_serve_reprobe_s=0.0)
    base.update(over)
    return Config.from_params(base)


# ---------------------------------------------------------------------
# circuit breaker unit behavior
# ---------------------------------------------------------------------

def test_breaker_trips_and_half_opens():
    br = CircuitBreaker(trip_after=2, backoff_base_s=0.05,
                        backoff_cap_s=0.1, seed=0)
    assert br.allow() and br.state == "closed"
    br.record_failure(RuntimeError("UNAVAILABLE: hiccup"))
    assert br.state == "closed"          # one transient is not a trip
    br.record_failure(RuntimeError("UNAVAILABLE: hiccup"))
    assert br.state == "open" and not br.allow()
    time.sleep(0.08)
    assert br.allow() and br.state == "half_open"  # exactly one probe
    assert not br.allow()                # second concurrent probe denied
    br.record_ok()
    assert br.state == "closed" and br.allow()


def test_breaker_fatal_trips_immediately_and_reopens_on_probe_failure():
    br = CircuitBreaker(trip_after=5, backoff_base_s=0.03,
                        backoff_cap_s=0.05, seed=1)
    assert br.record_failure(ValueError("nonsense")) == "fatal"
    assert br.state == "open" and br.opens == 1
    time.sleep(0.05)
    assert br.allow()                    # half-open probe
    br.record_failure(RuntimeError("UNAVAILABLE: still dead"))
    assert br.state == "open" and br.opens == 2  # probe failure reopens


# ---------------------------------------------------------------------
# replica router
# ---------------------------------------------------------------------

def test_router_failover_on_wedged_replica(fleet_models):
    m1, b1, _, _, X = fleet_models
    router = ReplicaRouter(m1, n_replicas=2, config=_cfg())
    ref = b1.predict(X[:8])
    try:
        faults.configure("serve_replica_0:raise@n=-1")
        for _ in range(6):
            t = router.submit(X[:8])
            assert t.replica.idx == 1    # survivor carries the traffic
            assert np.allclose(router.result(t, timeout=30), ref,
                               atol=1e-6)
        st = router.stats()
        assert st["replicas"][0]["breaker"]["state"] in ("open",
                                                         "half_open")
        assert st["failovers"] >= 1
        assert not st["degraded"]        # fleet still serving
        faults.disarm()
        # half-open probe re-admits replica 0 once the backoff passes
        deadline = time.time() + 10
        while (router.replicas[0].breaker.state != "closed"
               and time.time() < deadline):
            router.result(router.submit(X[:4]), timeout=30)
            time.sleep(0.1)
        assert router.replicas[0].breaker.state == "closed"
        assert router.routable_count() == 2
    finally:
        router.close()


def test_router_drain_removes_replica_from_routing(fleet_models):
    m1, _, _, _, X = fleet_models
    router = ReplicaRouter(m1, n_replicas=2, config=_cfg())
    try:
        router.drain(0)
        for _ in range(4):
            t = router.submit(X[:4])
            assert t.replica.idx == 1
        assert router.routable_count() == 1
        router.undrain(0)
        assert router.routable_count() == 2
    finally:
        router.close()


def test_router_all_replicas_down_raises_overload(fleet_models):
    m1, _, _, _, X = fleet_models
    router = ReplicaRouter(m1, n_replicas=2, config=_cfg())
    try:
        router.drain(0)
        router.drain(1)
        with pytest.raises(ServeOverloadError):
            router.submit(X[:4])
    finally:
        router.close()


# ---------------------------------------------------------------------
# registry: swap / canary / rollback
# ---------------------------------------------------------------------

def test_swap_flips_and_rollback_restores(fleet_models):
    m1, b1, m2, b2, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=1)
    try:
        reg.add_model("default", m1)
        t = reg.submit(X[:8])
        assert t.version == 1
        assert np.allclose(reg.result(t), b1.predict(X[:8]), atol=1e-6)

        report = reg.swap("default", m2)
        assert report["ok"] and report["to_version"] == 2
        assert report["canary"]["checks"]["parity"]
        t2 = reg.submit(X[:8])
        assert t2.version == 2
        assert np.allclose(reg.result(t2), b2.predict(X[:8]), atol=1e-6)

        rb = reg.rollback("default", reason="test")
        assert rb["to_version"] == 1
        t3 = reg.submit(X[:8])
        assert t3.version == 1
        assert np.allclose(reg.result(t3), b1.predict(X[:8]), atol=1e-6)
        row = reg.models()[0]
        assert row["swaps"] == 1 and row["rollbacks"] == 1
        # after a rollback nothing is resident to roll back to
        with pytest.raises(RuntimeError):
            reg.rollback("default")
    finally:
        reg.close()


def test_canary_rejection_leaves_old_model_serving(fleet_models):
    m1, b1, m2, _, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=1)
    try:
        reg.add_model("default", m1)
        faults.configure("serve_canary:raise@call=1")
        with pytest.raises(SwapRejected):
            reg.swap("default", m2)
        faults.disarm()
        row = reg.models()[0]
        assert row["live_version"] == 1 and row["swaps_rejected"] == 1
        t = reg.submit(X[:8])
        assert t.version == 1
        assert np.allclose(reg.result(t), b1.predict(X[:8]), atol=1e-6)
    finally:
        reg.close()


def test_injected_swap_fault_aborts_before_flip(fleet_models):
    m1, b1, m2, _, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=1)
    try:
        reg.add_model("default", m1)
        faults.configure("serve_swap:raise@call=1")
        with pytest.raises(SwapRejected):
            reg.swap("default", m2)
        faults.disarm()
        assert reg.resolve(None).version == 1
        t = reg.submit(X[:4])
        assert np.allclose(reg.result(t), b1.predict(X[:4]), atol=1e-6)
    finally:
        reg.close()


def test_postswap_regression_triggers_auto_rollback(fleet_models,
                                                    tmp_path,
                                                    monkeypatch):
    m1, b1, m2, _, X = fleet_models
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    reg = ModelRegistry(config=_cfg(tpu_serve_rollback_degraded=1),
                        n_replicas=1)
    try:
        reg.add_model("default", m1)
        assert reg.swap("default", m2)["ok"]
        faults.configure("serve_device:raise@n=-1")
        for _ in range(3):   # degrade v2 (host fallback keeps serving)
            reg.result(reg.submit(X[:4]), timeout=30)
        out = reg.check_postswap("default")
        faults.disarm()
        assert out is not None and str(out["reason"]).startswith("auto:")
        assert reg.resolve(None).version == 1
        assert list(tmp_path.glob("FLIGHT_*.json"))  # rollback post-mortem
        t = reg.submit(X[:4])
        assert np.allclose(reg.result(t), b1.predict(X[:4]), atol=1e-6)
    finally:
        faults.disarm()
        reg.close()


def test_swap_under_concurrent_mixed_traffic_is_loss_free(fleet_models):
    """The tentpole contract: a hot swap under concurrent mixed
    predict + explain traffic loses nothing, and every response is
    bit-consistent with the version it claims."""
    m1, b1, m2, b2, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=1)
    expected = {
        1: (b1.predict(X[:32]), b1.predict(X[:32], pred_contrib=True)),
        2: (b2.predict(X[:32]), b2.predict(X[:32], pred_contrib=True)),
    }
    results, lock = [], threading.Lock()
    stop = threading.Event()

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            n = int(rng.integers(1, 9))
            lo = int(rng.integers(0, 32 - n + 1))
            explain = rng.random() < 0.3
            try:
                if explain:
                    t = reg.submit_explain(X[lo:lo + n])
                else:
                    t = reg.submit(X[lo:lo + n])
                out = reg.result(t, timeout=60)
                with lock:
                    results.append((t.version, explain, lo, n,
                                    np.asarray(out)))
            except Exception as exc:  # noqa: BLE001 — counted as loss
                with lock:
                    results.append((None, explain, lo, n, repr(exc)))
            time.sleep(0.005)

    try:
        reg.add_model("default", m1)
        threads = [threading.Thread(target=client, args=(s,))
                   for s in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.4)
        assert reg.swap("default", m2)["ok"]
        time.sleep(0.4)
        stop.set()
        for t in threads:
            t.join(30)
        lost = [r for r in results if r[0] is None]
        assert not lost, lost[:3]
        assert len(results) > 10
        versions = {r[0] for r in results}
        assert versions == {1, 2}
        for ver, explain, lo, n, out in results:
            want = expected[ver][1 if explain else 0][lo:lo + n]
            assert out.shape == np.asarray(want).shape
            assert np.allclose(out, want, atol=1e-5), (ver, explain, lo)
    finally:
        stop.set()
        reg.close()


# ---------------------------------------------------------------------
# priority shedding
# ---------------------------------------------------------------------

def test_low_priority_sheds_first(fleet_models, monkeypatch):
    m1, _, _, _, X = fleet_models
    sess = PredictorSession(m1, config=_cfg(
        tpu_serve_max_batch=16, tpu_serve_queue_depth=64,
        tpu_serve_max_wait_ms=50.0))
    orig = sess._run_device

    def slow(bins, **kw):
        time.sleep(0.1)
        return orig(bins, **kw)

    monkeypatch.setattr(sess, "_run_device", slow)
    tickets = [sess.submit(X[:8], priority="normal") for _ in range(6)]
    with pytest.raises(ServeOverloadError) as exc_info:
        sess.submit(X[:8], priority="low")
    assert exc_info.value.priority == "low" and exc_info.value.shed
    tickets.append(sess.submit(X[:8], priority="high"))
    for t in tickets:
        sess.result(t, timeout=60)
    snap = sess.metrics.snapshot()
    assert snap["shed_by_priority"].get("low") == 1
    assert snap["shed_by_priority"].get("high") is None
    assert snap["served_by_priority"].get("high") == 1
    assert snap["served_by_priority"].get("normal") == 6
    sess.close()


def test_unknown_priority_serves_as_normal(fleet_models):
    m1, _, _, _, X = fleet_models
    sess = PredictorSession(m1, config=_cfg())
    t = sess.submit(X[:4], priority="urgent-nonsense")
    sess.result(t, timeout=30)
    assert sess.metrics.snapshot()["served_by_priority"] == {"normal": 1}
    sess.close()


# ---------------------------------------------------------------------
# HTTP fleet surface
# ---------------------------------------------------------------------

def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def test_http_fleet_roundtrip_swap_and_models(fleet_models):
    m1, b1, m2, b2, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=2)
    reg.add_model("default", m1)
    server = PredictServer(reg).start()
    url = server.url
    try:
        code, body, _ = _post(url + "/predict",
                              {"rows": X[:4].tolist(),
                               "priority": "high"})
        assert code == 200 and body["version"] == 1
        assert body["model"] == "default" and "replica" in body
        assert np.allclose(body["predictions"], b1.predict(X[:4]),
                           atol=1e-6)
        # /models listing + per-model health
        with urllib.request.urlopen(url + "/models", timeout=30) as r:
            listing = json.loads(r.read())
        assert listing["default"] == "default"
        assert listing["models"][0]["live_version"] == 1
        with urllib.request.urlopen(url + "/health", timeout=30) as r:
            health = json.loads(r.read())
        assert health["status"] == "ok"
        assert len(health["replicas"]) == 2
        assert health["models"]["default"]["live_version"] == 1
        # swap over HTTP, then traffic reflects v2
        code, rep, _ = _post(url + "/models/default/swap",
                             {"model_file": m2}, timeout=120)
        assert code == 200 and rep["ok"] and rep["to_version"] == 2
        code, body, _ = _post(url + "/predict", {"rows": X[:4].tolist()})
        assert body["version"] == 2
        assert np.allclose(body["predictions"], b2.predict(X[:4]),
                           atol=1e-6)
        # rollback over HTTP
        code, rb, _ = _post(url + "/models/default/rollback",
                            {"reason": "test"})
        assert code == 200 and rb["to_version"] == 1
        code, body, _ = _post(url + "/predict", {"rows": X[:4].tolist()})
        assert body["version"] == 1
        # unknown model -> 404
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(url + "/predict", {"rows": X[:4].tolist(),
                                     "model": "nope"})
        assert err.value.code == 404
    finally:
        server.stop(close_session=True)


def test_http_fleet_metrics_exposition(fleet_models):
    from lightgbm_tpu.serve import parse_prometheus
    m1, _, _, _, X = fleet_models
    reg = ModelRegistry(config=_cfg(), n_replicas=2)
    reg.add_model("default", m1)
    server = PredictServer(reg).start()
    try:
        _post(server.url + "/predict", {"rows": X[:4].tolist()})
        with urllib.request.urlopen(server.url + "/metrics",
                                    timeout=30) as r:
            pm = parse_prometheus(r.read().decode())
        assert pm.get('tpu_serve_model_version{model="default"}') == 1.0
        assert pm.get('tpu_serve_swaps_total{model="default"}') == 0.0
        assert pm.get('tpu_serve_rollbacks_total{model="default"}') == 0.0
        assert 'tpu_serve_replica_healthy{replica="r0"}' in pm
        assert 'tpu_serve_replica_breaker_state{replica="r1"}' in pm
        assert 'tpu_serve_shed_total{priority="low"}' in pm
        assert pm.get('tpu_serve_served_total{priority="normal"}') >= 1.0
    finally:
        server.stop(close_session=True)


def test_bare_session_server_unchanged(fleet_models):
    """Back-compat: a server over a bare session has no fleet fields and
    404s the fleet endpoints."""
    m1, b1, _, _, X = fleet_models
    sess = PredictorSession(m1, config=_cfg())
    server = PredictServer(sess).start()
    try:
        code, body, _ = _post(server.url + "/predict",
                              {"rows": X[:3].tolist()})
        assert code == 200 and "version" not in body
        assert np.allclose(body["predictions"], b1.predict(X[:3]),
                           atol=1e-6)
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(server.url + "/models", timeout=30)
        assert err.value.code == 404
    finally:
        server.stop(close_session=True)


# ---------------------------------------------------------------------
# telemetry schemas
# ---------------------------------------------------------------------

def test_fleet_events_validate(fleet_models, tmp_path):
    from lightgbm_tpu.obs.report import (load_events, serve_summary,
                                         validate_events)
    m1, _, m2, _, X = fleet_models
    obs.enable(str(tmp_path / "telem"))
    try:
        reg = ModelRegistry(config=_cfg(), n_replicas=2)
        reg.add_model("default", m1)
        reg.swap("default", m2)
        reg.result(reg.submit(X[:4]))
        reg.rollback("default", reason="test")
        faults.configure("serve_replica_0:raise@n=1")
        router = reg.resolve(None).router
        router.result(router.submit(X[:4]))
    finally:
        faults.disarm()
        reg.close()
        obs.disable()
    events = load_events(str(tmp_path / "telem"))
    names = {e.get("event") for e in events}
    assert {"serve_swap", "serve_canary", "serve_rollback"} <= names
    problems = validate_events(events)
    assert not problems, problems[:5]
    digest = serve_summary(events)
    # the initial deploy is counted apart from real hot-swaps (matching
    # the registry's swaps counter and tpu_serve_swaps_total)
    assert digest["fleet"]["swaps"] == 1
    assert digest["fleet"]["deploys"] == 1
    assert digest["fleet"]["rollbacks"] == 1
