"""Native text ingestion + two_round streaming loading.

The reference reads big files through a buffered sampling reader and a
double-buffered pipeline (utils/text_reader.h:1-341, utils/
pipeline_reader.h) and offers two_round loading that trades a second file
pass for not materializing the raw matrix (config.h two_round,
dataset_loader.cpp:807-827).  Here: the native chunk parser must be
bit-identical to np.loadtxt, and two_round must produce the exact same
BinnedDataset as the in-memory path.
"""
import os

import numpy as np
import pytest

from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset
from lightgbm_tpu.io.text_loader import (_read_dense, load_text,
                                         load_text_two_round)


def _write_csv(path, data, delim=",", header=None):
    with open(path, "w") as fh:
        if header:
            fh.write(delim.join(header) + "\n")
        for row in data:
            fh.write(delim.join(
                "nan" if np.isnan(v) else repr(float(v)) for v in row) + "\n")


@pytest.fixture
def csv_problem(tmp_path):
    rng = np.random.default_rng(5)
    n = 600
    y = rng.integers(0, 2, n).astype(float)
    X = np.stack([rng.normal(size=n).round(3),
                  rng.integers(0, 12, n).astype(float),
                  rng.normal(size=n) * 1e5], axis=1)
    X[rng.random(n) < 0.05, 0] = np.nan
    data = np.column_stack([y, X])
    path = str(tmp_path / "train.csv")
    _write_csv(path, data)
    return path, data


def test_read_dense_bitmatches_loadtxt(csv_problem):
    path, data = csv_problem
    got = _read_dense(path, ",", 0)
    ref = np.loadtxt(path, delimiter=",", ndmin=2)
    assert got.shape == ref.shape
    assert np.array_equal(got, ref, equal_nan=True)
    # the written doubles round-trip exactly (repr -> strtod-exact parse)
    assert np.array_equal(got, data, equal_nan=True)


def test_read_dense_tabs_header_crlf(tmp_path):
    p = str(tmp_path / "t.tsv")
    with open(p, "wb") as fh:
        # "na" is the reference's missing token (Common::Atof); loadtxt
        # can't read it, the native parser must
        fh.write(b"a\tb\tc\r\n1\t2.5\t-3e2\r\nna\t0\t4\r\n")
    got = _read_dense(p, "\t", 1)
    assert np.array_equal(got, [[1, 2.5, -300], [np.nan, 0, 4]],
                          equal_nan=True)


def test_read_dense_small_chunks(csv_problem):
    """Chunk boundaries never split or drop rows."""
    from lightgbm_tpu.io.text_loader import _iter_dense_chunks
    path, data = csv_problem
    parts = list(_iter_dense_chunks(path, ",", 0, chunk_bytes=999))
    assert len(parts) > 3
    assert np.array_equal(np.vstack(parts), data, equal_nan=True)


def test_two_round_matches_in_memory(csv_problem, tmp_path):
    """two_round streaming must construct the EXACT same dataset as the
    in-memory path when the bin sample covers all rows."""
    path, data = csv_problem
    wpath = path + ".weight"
    np.savetxt(wpath, np.linspace(0.5, 2.0, len(data)))
    cfg = Config.from_params({"verbose": -1, "max_bin": 63})

    X, label, weight, group, names = load_text(path, cfg)
    h1 = BinnedDataset.from_matrix(X, cfg, categorical_features=[1],
                                   feature_names=names)
    h2, label2, weight2, group2, names2 = load_text_two_round(
        path, cfg, categorical_features=[1])

    assert names2 == names
    np.testing.assert_array_equal(label2, label)
    np.testing.assert_array_equal(weight2, weight)
    assert group2 is None and group is None
    assert h2.num_data == h1.num_data
    np.testing.assert_array_equal(h2.X_bin, h1.X_bin)
    np.testing.assert_array_equal(h2.bin_offsets, h1.bin_offsets)
    for m1, m2 in zip(h1.bin_mappers, h2.bin_mappers):
        assert m1.bin_type == m2.bin_type
        np.testing.assert_array_equal(np.asarray(m1.bin_upper_bound),
                                      np.asarray(m2.bin_upper_bound))

    # valid-set alignment: reference mappers reused exactly
    h3, label3, _, _, _ = load_text_two_round(path, cfg, reference=h1)
    np.testing.assert_array_equal(h3.X_bin, h1.X_bin)
    assert h3.bin_mappers is h1.bin_mappers


def test_two_round_reservoir_subsample(csv_problem):
    """n > bin_construct_sample_cnt takes the reservoir path; bins stay
    within max_bin and the dataset is fully constructed."""
    path, data = csv_problem
    cfg = Config.from_params({"verbose": -1, "max_bin": 31,
                              "bin_construct_sample_cnt": 100})
    h, label, _, _, _ = load_text_two_round(path, cfg)
    assert h.num_data == len(data)
    assert len(label) == len(data)
    assert h.X_bin.shape[0] == len(data)
    assert int(h.feature_max_bins().max()) <= 32
    # every row binned (no leftover uninitialized garbage): max bin value
    # must be < the per-feature bin count
    for inner in range(h.num_features):
        assert h.X_bin[:, inner].max() < h.num_bin(inner)


def test_two_round_cli_matches_one_round(csv_problem, tmp_path):
    """CLI task=train with two_round=true produces the same model as the
    default load (sample covers all rows -> identical mappers)."""
    from lightgbm_tpu.app import main
    path, _ = csv_problem
    outs = []
    for i, extra in enumerate(["two_round=false", "two_round=true"]):
        out = str(tmp_path / f"model{i}.txt")
        main(["task=train", f"data={path}", "objective=binary",
              "num_trees=8", "num_leaves=7", "verbose=-1",
              f"output_model={out}", extra])
        outs.append(open(out).read())
    # identical up to the echoed parameter block (paths/two_round differ)
    strip = [l for l in outs[0].splitlines()
             if not l.startswith("[") and l != "end of parameters"]
    strip2 = [l for l in outs[1].splitlines()
              if not l.startswith("[") and l != "end of parameters"]
    assert strip == strip2


def test_parse_cols_trailing_delim_and_garbage():
    """Review-found edge cases: a trailing delimiter after the last wanted
    column must not read past the cols array, and garbage-prefixed fields
    ("3.14.15") abort the strict parse — never a silent prefix, never a
    fabricated NaN (the lenient np.loadtxt fallback surfaces the error)."""
    from lightgbm_tpu import native
    got = native.csv_parse_cols(b"5,1,\n7,2,\n", ",", [0])
    np.testing.assert_array_equal(got, [[5], [7]])
    assert native.csv_parse(b"3.14.15,2\n", ",", 2) is None
    assert native.csv_parse(b"12abc,4\n", ",", 2) is None
    assert native.csv_parse_cols(b"1,3.14.15\n", ",", [1]) is None


def test_libsvm_nan_labels_rejected_unconditionally():
    """ADVICE.md: any NaN label — garbage OR a literal na/nan token —
    must abort the strict LibSVM parse (None -> Python fallback raises),
    never train on NaN targets.  Feature VALUES stay NaN-tolerant."""
    from lightgbm_tpu import native
    if native.lib() is None:
        pytest.skip("native library unavailable")
    assert native.libsvm_parse(b"n0pe 1:0.5\n") is None     # typo'd label
    assert native.libsvm_parse(b"nan 1:0.5\n") is None      # literal token
    assert native.libsvm_parse(b"na 1:0.5\n") is None
    assert native.libsvm_parse(b"1 1:1\nNaN 1:2\n") is None  # mid-chunk
    out = native.libsvm_parse(b"1 qid:3 1:na 2:0.5\n")       # NA feature ok
    assert out is not None
    labels, _, _, _, vals, _ = out
    assert labels[0] == 1 and np.isnan(vals[0]) and vals[1] == 0.5


def test_is_na_token_exact_set():
    """ADVICE.md: the NA token set is exact (na/nan/null/n/a/empty/?,
    case-insensitive) — an n-prefixed typo is NOT silently missing: it
    aborts the strict parse (malformed-row return) so the lenient
    fallback surfaces the real error."""
    from lightgbm_tpu import native
    if native.lib() is None:
        pytest.skip("native library unavailable")
    got = native.csv_parse(b"na,NaN,NULL,n/a,?, \n", ",", 6)
    assert np.isnan(got).all(), got
    # glibc printf renders negative NaN as "-nan"; sign-prefixed nan is
    # in the token set, but the sign blesses nan only
    got = native.csv_parse(b"-nan,+NaN\n", ",", 2)
    assert np.isnan(got).all(), got
    assert native.csv_parse(b"-na,1\n", ",", 2) is None
    assert native.csv_parse(b"-n/a,1\n", ",", 2) is None
    for typo in (b"n0.5,2\n", b"none,4\n", b"noNe3,6\n", b"negative,1\n"):
        assert native.csv_parse(typo, ",", 2) is None, typo
    assert native.libsvm_parse(b"n0.5 1:1\n") is None
    assert native.libsvm_parse(b"none 1:1\n") is None
    # numbers and NA tokens still coexist on one row
    got = native.csv_parse(b"1.5,na,2e3\n", ",", 3)
    assert got[0, 0] == 1.5 and np.isnan(got[0, 1]) and got[0, 2] == 2000


def test_two_round_no_trailing_newline(tmp_path):
    """A final line without a newline must survive reservoir sampling in
    any slot (lines are re-joined with per-line separators)."""
    rng = np.random.default_rng(0)
    n = 400
    data = np.column_stack([rng.integers(0, 2, n),
                            rng.normal(size=(n, 3)).round(2)])
    path = str(tmp_path / "nonl.csv")
    body = "\n".join(",".join(repr(float(v)) for v in row) for row in data)
    with open(path, "w") as fh:
        fh.write(body)  # no trailing newline
    cfg = Config.from_params({"verbose": -1, "max_bin": 31,
                              "bin_construct_sample_cnt": 50})
    h, label, _, _, _ = load_text_two_round(path, cfg)
    assert h.num_data == n
    np.testing.assert_array_equal(label, data[:, 0])


def test_libsvm_qid_native_matches_python(tmp_path):
    """LibSVM with qid: tokens (the real MSLR-WEB30K format): the native
    parser and the Python fallback agree, rows come back as sparse CSR,
    and qids become query boundaries."""
    import scipy.sparse as sp
    from lightgbm_tpu.io.text_loader import _load_libsvm
    p = str(tmp_path / "rank.svm")
    with open(p, "w") as fh:
        fh.write("2 qid:1 1:0.5 4:1.25\n"
                 "0 qid:1 0:3 2:-0.5\n"
                 "1 qid:2 4:2e-1\n"
                 "0 qid:2 1:1 3:7\n")
    cfg = Config.from_params({"verbose": -1})
    X, label, weight, group, names = _load_libsvm(p, cfg)
    assert sp.issparse(X) and X.shape == (4, 5)
    np.testing.assert_array_equal(label, [2, 0, 1, 0])
    np.testing.assert_array_equal(group, [2, 2])  # qid run lengths
    np.testing.assert_allclose(X.toarray()[0], [0, 0.5, 0, 0, 1.25])
    # python fallback parses identically
    import lightgbm_tpu.native as _native
    old_lib, old_tried = _native._lib, _native._tried
    _native._lib, _native._tried = None, True
    try:
        X2, label2, _, group2, _ = _load_libsvm(p, cfg)
    finally:
        _native._lib, _native._tried = old_lib, old_tried
    np.testing.assert_array_equal(X.toarray(), X2.toarray())
    np.testing.assert_array_equal(label, label2)
    np.testing.assert_array_equal(group, group2)


def test_libsvm_qid_trains_lambdarank(tmp_path):
    """End to end: a qid: LibSVM file drives lambdarank through the CLI
    loader path without a .query sidecar."""
    rng = np.random.default_rng(4)
    p = str(tmp_path / "mslr.svm")
    with open(p, "w") as fh:
        for q in range(40):
            for _ in range(rng.integers(5, 15)):
                rel = rng.integers(0, 3)
                feats = " ".join(
                    f"{j}:{rng.normal() + rel:.3f}"
                    for j in sorted(rng.choice(30, size=10, replace=False)))
                fh.write(f"{rel} qid:{q} {feats}\n")
    from lightgbm_tpu.io.text_loader import load_text
    cfg = Config.from_params({"verbose": -1})
    X, label, weight, group, names = load_text(p, cfg)
    assert group is not None and group.sum() == len(label)
    import lightgbm_tpu as lgb
    ds = lgb.Dataset(X, label=label, group=group,
                     params={"objective": "lambdarank", "verbose": -1})
    bst = lgb.train({"objective": "lambdarank", "metric": "ndcg",
                     "eval_at": [5], "num_leaves": 7, "min_data_in_leaf": 5,
                     "verbose": -1}, ds, num_boost_round=5,
                    valid_sets=[ds], valid_names=["t"])
    res = bst.eval_train()
    assert any("ndcg" in m for (_, m, v, _) in res)


def test_libsvm_predict_file_narrower_than_model(tmp_path):
    """A prediction LibSVM file whose highest feature indices are absent
    must pad implicit-zero columns to the model's feature count (the
    reference pads the same way) instead of mis-indexing."""
    import scipy.sparse as sp
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(0)
    n = 300
    X = rng.normal(size=(n, 6))
    y = (X[:, 5] > 0).astype(float)  # the LAST feature carries signal
    bst = lgb.train({"objective": "binary", "num_leaves": 7,
                     "min_data_in_leaf": 5, "verbose": -1},
                    lgb.Dataset(X, label=y), num_boost_round=5)
    # prediction rows only mention features 0..2 -> CSR with 3 columns
    Xp = sp.csr_matrix(np.hstack([X[:20, :3]]))
    out = bst.predict(Xp)
    assert out.shape == (20,)
    # equivalent dense rows (features 3..5 = 0) give identical output
    dense = np.zeros((20, 6))
    dense[:, :3] = X[:20, :3]
    np.testing.assert_allclose(out, bst.predict(dense), atol=1e-12)
