"""Categorical split search correctness.

The device search (core/splitter.py::_categorical_best) is checked
gain-for-gain against a scalar numpy oracle transcribing the reference's
FindBestThresholdCategorical (reference:
src/treelearner/feature_histogram.hpp:118-279), and the full chain —
train with declared categorical features, category-set partitions, model
text round-trip, device vs host prediction — is exercised end-to-end.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.core.grower import make_grower
from lightgbm_tpu.core.meta import DeviceMeta, SplitConfig, build_device_meta
from lightgbm_tpu.core import splitter
from lightgbm_tpu.core.wave_grower import build_wave_grow_fn

K_EPSILON = 1e-15
FIX = os.path.join(os.path.dirname(__file__), "fixtures")


# ---------------------------------------------------------------------------
# scalar oracle (reference: feature_histogram.hpp:118-279)
# ---------------------------------------------------------------------------

def _leaf_gain(g, h, l1, l2):
    s = np.sign(g) * max(abs(g) - l1, 0.0)
    return s * s / (h + l2)


def _split_gain(gl, hl, gr, hr, l1, l2):
    return _leaf_gain(gl, hl, l1, l2) + _leaf_gain(gr, hr, l1, l2)


def oracle_categorical(g, h, c, sum_g, sum_h, cnt, num_bin, missing_none,
                       cfg: SplitConfig):
    """Best categorical split of one feature; returns
    (gain_above_min_shift, left_bin_set) or (-inf, None)."""
    gain_shift = _leaf_gain(sum_g, sum_h, cfg.lambda_l1, cfg.lambda_l2)
    min_gain_shift = gain_shift + cfg.min_gain_to_split
    used_bin = num_bin - 1 + int(missing_none)
    l2 = cfg.lambda_l2
    best_gain, best_set = -np.inf, None

    if num_bin <= cfg.max_cat_to_onehot:
        for t in range(used_bin):
            if c[t] < cfg.min_data_in_leaf or h[t] < cfg.min_sum_hessian_in_leaf:
                continue
            if cnt - c[t] < cfg.min_data_in_leaf:
                continue
            oh = sum_h - h[t] - K_EPSILON
            if oh < cfg.min_sum_hessian_in_leaf:
                continue
            gain = _split_gain(sum_g - g[t], oh, g[t], h[t] + K_EPSILON,
                               cfg.lambda_l1, l2)
            if gain <= min_gain_shift:
                continue
            if gain > best_gain:
                best_gain, best_set = gain, {t}
    else:
        sorted_idx = [i for i in range(used_bin) if c[i] >= cfg.cat_smooth]
        l2 += cfg.cat_l2
        sorted_idx.sort(key=lambda i: g[i] / (h[i] + cfg.cat_smooth))
        ub = len(sorted_idx)
        max_num_cat = min(cfg.max_cat_threshold, (ub + 1) // 2)
        for dir_, start in ((1, 0), (-1, ub - 1)):
            grp = 0
            lg, lh, lc = 0.0, K_EPSILON, 0.0
            pos = start
            for i in range(min(ub, max_num_cat)):
                t = sorted_idx[pos]
                pos += dir_
                lg += g[t]; lh += h[t]; lc += c[t]; grp += c[t]
                if (lc < cfg.min_data_in_leaf
                        or lh < cfg.min_sum_hessian_in_leaf):
                    continue
                rc = cnt - lc
                if rc < cfg.min_data_in_leaf or rc < cfg.min_data_per_group:
                    break
                rh = sum_h - lh
                if rh < cfg.min_sum_hessian_in_leaf:
                    break
                if grp < cfg.min_data_per_group:
                    continue
                grp = 0
                gain = _split_gain(lg, lh, sum_g - lg, rh, cfg.lambda_l1, l2)
                if gain <= min_gain_shift:
                    continue
                if gain > best_gain:
                    best_gain = gain
                    if dir_ == 1:
                        best_set = set(sorted_idx[: i + 1])
                    else:
                        best_set = set(sorted_idx[ub - 1 - i:])
    if best_set is None:
        return -np.inf, None
    return best_gain - min_gain_shift, best_set


def _unpack(words, B):
    return {b for b in range(B) if (int(words[b // 32]) >> (b % 32)) & 1}


def _cat_meta(num_bins):
    F = len(num_bins)
    return DeviceMeta(
        num_bins=jnp.asarray(num_bins, jnp.int32),
        default_bins=jnp.zeros(F, jnp.int32),
        missing_types=jnp.zeros(F, jnp.int32),   # MISSING_NONE
        monotone=jnp.zeros(F, jnp.int32),
        penalties=jnp.ones(F, jnp.float32),
        is_categorical=jnp.ones(F, bool),
    )


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
@pytest.mark.parametrize("onehot", [False, True])
def test_categorical_search_matches_reference_oracle(seed, onehot):
    rng = np.random.default_rng(seed)
    B = 24
    cfg = SplitConfig(num_leaves=31, min_data_in_leaf=3,
                      min_sum_hessian_in_leaf=1e-3, min_data_per_group=5,
                      cat_smooth=2.0, cat_l2=1.0,
                      max_cat_to_onehot=(64 if onehot else 4))
    for trial in range(6):
        nb = int(rng.integers(6, B + 1))
        c = np.zeros(B); g = np.zeros(B); h = np.zeros(B)
        c[:nb] = rng.integers(0, 30, size=nb).astype(float)
        g[:nb] = rng.normal(size=nb) * c[:nb] * 0.1
        h[:nb] = c[:nb] * (0.2 + 0.1 * rng.random(nb))
        sg, sh, sc = g.sum(), h.sum() + 2 * K_EPSILON, c.sum()
        if sc < 2 * cfg.min_data_in_leaf:
            continue
        hist = jnp.asarray(np.stack([g, h, c], axis=-1)[None], jnp.float32)
        bs = splitter.best_split(hist, jnp.float32(sg), jnp.float32(sh - 2 * K_EPSILON),
                                 jnp.float32(sc), _cat_meta([nb]), cfg,
                                 jnp.float32(-np.inf), jnp.float32(np.inf))
        want_gain, want_set = oracle_categorical(
            g, h, c, sg, sh, sc, nb, True, cfg)
        if want_set is None:
            assert float(bs.gain) == -np.inf, (
                f"trial {trial}: oracle found no split, device gain={float(bs.gain)}")
            continue
        np.testing.assert_allclose(float(bs.gain), want_gain, rtol=2e-4,
                                   err_msg=f"trial {trial} gain mismatch")
        got_set = _unpack(np.asarray(bs.cat_bitset), B)
        assert got_set == want_set, f"trial {trial}: {got_set} != {want_set}"


# ---------------------------------------------------------------------------
# end-to-end
# ---------------------------------------------------------------------------

def _cat_problem(n=2000, seed=7):
    rng = np.random.default_rng(seed)
    cat = rng.integers(0, 12, size=n).astype(np.float64)
    x1 = rng.normal(size=n)
    logit = 2.5 * ((cat % 3 == 0).astype(np.float64) - 0.5) + 0.4 * x1
    y = (logit + rng.normal(scale=0.5, size=n) > 0).astype(np.float64)
    X = np.column_stack([cat, x1, rng.normal(size=n)])
    X[rng.random(n) < 0.02, 0] = np.nan
    return X, y


def test_categorical_train_roundtrip_and_predict():
    X, y = _cat_problem()
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "min_data_per_group": 20, "verbose": -1}
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], params=params)
    bst = lgb.train(params, ds, num_boost_round=15)
    txt = bst.model_to_string()
    n_cat = sum(int(l.split("=")[1]) for l in txt.splitlines()
                if l.startswith("num_cat="))
    assert n_cat > 0, "no categorical splits were made"

    pred = bst.predict(X)
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, pred) > 0.85

    bst2 = lgb.Booster(model_str=txt)
    np.testing.assert_allclose(bst2.predict(X), pred, atol=1e-12)


def test_categorical_device_replay_matches_host_predict():
    """The bin-space device traversal (used for valid-set replay) and the
    value-space host prediction agree on training data."""
    X, y = _cat_problem(seed=3)
    params = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.2,
              "min_data_per_group": 20, "metric": "binary_logloss",
              "verbose": -1}
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], params=params)
    vs = lgb.Dataset(X, label=y, categorical_feature=[0], params=params,
                     reference=ds)
    ev = {}
    bst = lgb.train(params, ds, num_boost_round=10, valid_sets=[vs],
                    valid_names=["v"],
                    callbacks=[lgb.record_evaluation(ev)])
    pred = bst.predict(X)
    eps = 1e-15
    ll = -np.mean(y * np.log(np.clip(pred, eps, 1))
                  + (1 - y) * np.log(np.clip(1 - pred, eps, 1)))
    np.testing.assert_allclose(ev["v"]["binary_logloss"][-1], ll, rtol=1e-5)


def test_load_reference_categorical_model_predict_parity():
    """tests/fixtures/ref_cat_model.txt was trained by the reference CLI
    (built from /root/reference) with categorical_feature=0 on a synthetic
    dataset; ref_cat_pred.npy holds its own predictions. Loading that
    model here must reproduce them — cross-framework categorical-decision
    parity (reference: tree.h:265-303 CategoricalDecision). The prediction
    rows include NaN, unseen (25, 40), and negative categories, which the
    reference routes right."""
    bst = lgb.Booster(model_file=os.path.join(FIX, "ref_cat_model.txt"))
    rows = np.load(os.path.join(FIX, "cat_rows.npy"))
    expected = np.load(os.path.join(FIX, "ref_cat_pred.npy"))
    np.testing.assert_allclose(bst.predict(rows), expected, atol=1e-12)


def test_wave_categorical_matches_serial():
    """Wave grower (capacity 1, interpret mode) reproduces the serial
    grower node-for-node on a dataset with a categorical feature."""
    X, y = _cat_problem(n=800, seed=5)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_per_group": 10, "min_data_in_leaf": 5, "verbose": -1}
    ds = lgb.Dataset(X, label=y, categorical_feature=[0], params=params)
    ds.construct()
    handle = ds._handle
    cfg = Config.from_params(params)
    meta, B = build_device_meta(handle, cfg)
    scfg = SplitConfig.from_config(cfg)
    rng = np.random.default_rng(1)
    n = handle.num_data
    g = jnp.asarray(rng.normal(size=n).astype(np.float32))
    h = jnp.asarray((0.1 + rng.random(size=n)).astype(np.float32))
    mask = jnp.ones((n,), jnp.float32)
    fmask = jnp.ones((handle.num_features,), bool)

    serial = make_grower(meta, scfg, B)
    t1, lid1 = serial(jnp.asarray(handle.X_bin), g, h, mask, fmask)
    wave = jax.jit(build_wave_grow_fn(meta, scfg, B, wave_capacity=1,
                                      highest=True, interpret=True))
    t2, lid2 = wave(jnp.asarray(np.ascontiguousarray(handle.X_bin.T)),
                    g, h, mask, fmask)

    nn = int(t1.num_leaves) - 1
    assert int(t2.num_leaves) == nn + 1
    np.testing.assert_array_equal(np.asarray(t1.split_feature[:nn]),
                                  np.asarray(t2.split_feature[:nn]))
    np.testing.assert_array_equal(np.asarray(t1.threshold_bin[:nn]),
                                  np.asarray(t2.threshold_bin[:nn]))
    np.testing.assert_array_equal(np.asarray(t1.default_left[:nn]),
                                  np.asarray(t2.default_left[:nn]))
    np.testing.assert_array_equal(np.asarray(t1.cat_bitset[:nn]),
                                  np.asarray(t2.cat_bitset[:nn]))
    # leaf values too — a wrong l2 (lambda_l2 vs +cat_l2) in the output
    # computation would keep the structure but change the outputs
    np.testing.assert_allclose(np.asarray(t1.leaf_value),
                               np.asarray(t2.leaf_value), rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_array_equal(np.asarray(lid1), np.asarray(lid2))
    # at least one categorical node must exist for this to be a real test
    assert np.any(np.asarray(t1.cat_bitset[:nn]) != 0)


def test_high_cardinality_categorical_uint16_path():
    """A categorical with > 256 distinct values widens X_bin to uint16 and
    disables the uint8 wave kernel; train + split + round-trip must still
    work end to end (reference: bin storage sizing, dataset.cpp)."""
    rng = np.random.default_rng(44)
    n = 4000
    cat = rng.integers(0, 400, n).astype(float)  # 400 categories
    x1 = rng.normal(size=n)
    # direct categorical signal (marginally learnable) + numeric term
    y = (((cat % 7) < 3).astype(float) + 0.5 * (x1 > 0)
         + rng.logistic(size=n) * 0.2 > 0.75).astype(np.float64)
    X = np.column_stack([cat, x1])
    p = {"objective": "binary", "num_leaves": 31, "verbose": -1,
         "min_data_in_leaf": 10, "max_cat_threshold": 64,
         "categorical_feature": [0]}
    ds = lgb.Dataset(X, label=y, params=p)
    ds.construct()
    assert ds._handle.X_bin.dtype == np.uint16
    bst = lgb.train(p, ds, 10)
    from sklearn.metrics import roc_auc_score
    auc = roc_auc_score(y, bst.predict(X))
    assert auc > 0.9, auc
    assert any(t["num_cat"] > 0 for t in bst.dump_model()["tree_info"])
    re = lgb.Booster(model_str=bst.model_to_string())
    np.testing.assert_allclose(re.predict(X), bst.predict(X), rtol=1e-6)
