"""Forced splits via forcedsplits_filename (reference:
serial_tree_learner.cpp:607-770 ForceSplits; config.h forcedsplits)."""
import json

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(n=800, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + 0.6 * X[:, 1] - 0.3 * X[:, 2] > 0).astype(np.float64)
    return X, y


PARAMS = {"objective": "binary", "num_leaves": 15, "verbose": -1,
          "min_data_in_leaf": 5, "learning_rate": 0.2}


def _train(tmp_path, forced_json, extra=None, rounds=5):
    X, y = _data()
    path = str(tmp_path / "forced.json")
    with open(path, "w") as fh:
        json.dump(forced_json, fh)
    p = dict(PARAMS, forcedsplits_filename=path, **(extra or {}))
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, num_boost_round=rounds)
    return bst, X, y


def test_root_split_is_forced(tmp_path):
    # feature 5 is pure noise — gain-driven growth would never pick it first
    bst, X, y = _train(tmp_path, {"feature": 5, "threshold": 0.0})
    d = bst.dump_model()
    for t in d["tree_info"]:
        assert t["tree_structure"]["split_feature"] == 5
    # the rest of the tree is gain-driven, so the model still learns
    from sklearn.metrics import roc_auc_score
    assert roc_auc_score(y, bst.predict(X)) > 0.85


def test_bfs_nesting_left_and_right(tmp_path):
    forced = {"feature": 5, "threshold": 0.0,
              "left": {"feature": 4, "threshold": 0.5},
              "right": {"feature": 3, "threshold": -0.5}}
    bst, X, y = _train(tmp_path, forced)
    root = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert root["split_feature"] == 5
    assert root["left_child"]["split_feature"] == 4
    assert root["right_child"]["split_feature"] == 3
    # thresholds round to the bin boundary containing the requested value
    assert abs(root["threshold"]) < 0.2


def test_rejected_forced_split_not_applied(tmp_path):
    # an impossible gain bar rejects the forced split exactly like the
    # reference's 'gain getting worse' path (GatherInfoForThreshold) —
    # and with it every split, so trees stay single-leaf
    forced = {"feature": 5, "threshold": 0.0,
              "left": {"feature": 4, "threshold": 0.0}}
    bst, X, y = _train(tmp_path, forced,
                       extra={"min_gain_to_split": 1e9}, rounds=2)
    root = bst.dump_model()["tree_info"][0]["tree_structure"]
    assert "split_feature" not in root  # single leaf: nothing was forced


def test_forced_split_roundtrips_model_text(tmp_path):
    bst, X, y = _train(tmp_path, {"feature": 5, "threshold": 0.0})
    txt = bst.model_to_string()
    re = lgb.Booster(model_str=txt)
    np.testing.assert_allclose(re.predict(X), bst.predict(X), rtol=1e-6)


def test_missing_file_is_fatal(tmp_path):
    X, y = _data()
    p = dict(PARAMS, forcedsplits_filename=str(tmp_path / "nope.json"))
    ds = lgb.Dataset(X, label=y, params=p)
    with pytest.raises(lgb.LightGBMError):
        lgb.train(p, ds, num_boost_round=2)


def test_reference_cli_forced_splits_parity():
    """Reference-CLI oracle: the captured model in tests/fixtures was
    trained by the reference binary with tests/fixtures/forced_splits.json
    on examples/binary_classification (num_trees=5, num_leaves=15,
    min_data_in_leaf=20, lr=0.1). Our run under the identical config must
    force the same BFS prefix — features AND (bin-boundary) thresholds —
    on every tree."""
    import os
    fix = os.path.join(os.path.dirname(__file__), "fixtures")
    ref_txt = open(os.path.join(fix, "ref_forced_splits_model.txt")).read()

    raw = np.loadtxt(
        "/root/reference/examples/binary_classification/binary.train")
    y, X = raw[:, 0], raw[:, 1:]
    p = {"objective": "binary", "num_leaves": 15, "learning_rate": 0.1,
         "min_data_in_leaf": 20, "verbose": -1,
         "forcedsplits_filename": os.path.join(fix, "forced_splits.json")}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 5)
    ours = bst.model_to_string()

    def split_rows(txt, key):
        return [ln.split("=", 1)[1].split() for ln in txt.splitlines()
                if ln.startswith(key + "=")]

    ref_feats = split_rows(ref_txt, "split_feature")
    our_feats = split_rows(ours, "split_feature")
    ref_thr = split_rows(ref_txt, "threshold")
    our_thr = split_rows(ours, "threshold")
    assert len(our_feats) == len(ref_feats) == 5
    for rf, of, rt, ot in zip(ref_feats, our_feats, ref_thr, our_thr):
        assert of[:3] == rf[:3] == ["25", "10", "4"]
        np.testing.assert_allclose([float(v) for v in ot[:3]],
                                   [float(v) for v in rt[:3]], rtol=1e-9)
