"""Config system tests (reference behavior: src/io/config.cpp, config_auto.cpp)."""
import pytest

from lightgbm_tpu.config import Config, read_config_file
from lightgbm_tpu.utils.log import LightGBMError


def test_defaults():
    cfg = Config()
    assert cfg.num_leaves == 31
    assert cfg.learning_rate == 0.1
    assert cfg.max_bin == 255
    assert cfg.objective == "regression"
    assert cfg.device_type == "tpu"


def test_aliases():
    cfg = Config.from_params({"n_estimators": 50, "eta": 0.3, "num_leaf": 63,
                              "min_child_samples": 5, "subsample": 0.5,
                              "colsample_bytree": 0.8, "reg_alpha": 1.0,
                              "reg_lambda": 2.0, "random_state": 7})
    assert cfg.num_iterations == 50
    assert cfg.learning_rate == 0.3
    assert cfg.num_leaves == 63
    assert cfg.min_data_in_leaf == 5
    assert cfg.bagging_fraction == 0.5
    assert cfg.feature_fraction == 0.8
    assert cfg.lambda_l1 == 1.0
    assert cfg.lambda_l2 == 2.0
    assert cfg.seed == 7


def test_objective_aliases():
    assert Config.from_params({"objective": "mse"}).objective == "regression"
    assert Config.from_params({"objective": "mae"}).objective == "regression_l1"
    assert Config.from_params({"application": "xentropy"}).objective == "cross_entropy"
    cfg = Config.from_params({"objective": "multiclass", "num_class": 3})
    assert cfg.num_model_per_iteration() == 3


def test_string_coercion():
    cfg = Config.from_params({"num_iterations": "25", "learning_rate": "0.05",
                              "is_unbalance": "true", "metric": "auc,binary_logloss"})
    assert cfg.num_iterations == 25
    assert cfg.learning_rate == 0.05
    assert cfg.is_unbalance is True
    assert cfg.metric == ["auc", "binary_logloss"]


def test_str2map():
    m = Config.str2map("task=train data=a.txt num_trees=10")
    assert m == {"task": "train", "data": "a.txt", "num_trees": "10"}


def test_validation_errors():
    with pytest.raises(LightGBMError):
        Config.from_params({"num_leaves": 1})
    with pytest.raises(LightGBMError):
        Config.from_params({"bagging_fraction": 0.0})
    with pytest.raises(LightGBMError):
        Config.from_params({"objective": "multiclass"})  # num_class missing
    with pytest.raises(LightGBMError):
        Config.from_params({"tree_learner": "bogus"})
    with pytest.raises(LightGBMError):
        Config.from_params({"tpu_wave_gain_gate": 1.5})
    with pytest.raises(LightGBMError):
        Config.from_params({"tpu_hist_dtype": "float16"})


def test_parallel_derivation():
    assert Config.from_params({"tree_learner": "data"}).is_parallel
    assert not Config.from_params({}).is_parallel


def test_config_file(tmp_path):
    p = tmp_path / "train.conf"
    p.write_text("# comment\ntask = train\nnum_trees = 7\n\nlearning_rate=0.2 # inline\n")
    params = read_config_file(str(p))
    cfg = Config.from_params(params)
    assert cfg.task == "train"
    assert cfg.num_iterations == 7
    assert cfg.learning_rate == 0.2


def test_multi_value_params_accept_sets():
    """The reference python-guide passes metric={'l2', 'l1'} — sets must
    coerce like lists (order made deterministic by sorting)."""
    cfg = Config.from_params({"metric": {"l2", "l1"}})
    assert cfg.metric == ["l1", "l2"]
    cfg2 = Config.from_params({"eval_at": (1, 3)})
    assert cfg2.eval_at == [1, 3]
