"""serve/arena.py + serve/aot.py — zero cold start & multi-tenant arena.

Pins the ISSUE 19 contracts on CPU:

- N tenant forests packed into one ``ForestArena`` (union bin space,
  per-tree model-id lane) predict BIT-identically to N dedicated
  ``PredictorSession``s on the dense / NaN / categorical / multiclass
  fixtures — converted and raw score, sync and async.
- Interleaved mixed-tenant submits coalesce into shared device batches.
- An impossible byte budget forces LRU eviction; the evicted tenant is
  transparently re-admitted, bit-identically, on its next request.
- AOT round-trip: a warmed store serves a fresh session's FULL pow2
  sweep with a compile-count delta of exactly zero, bit-identically,
  and request #1 lands within 2x the steady p99 (no hidden warm-up).
- A corrupt store entry falls back to JIT loudly (``aot_fallback``
  event + counter) with bit-identical output.
- Concurrent mixed-tenant HTTP traffic with a hot-swap of one tenant
  mid-storm: zero request loss, every response bit-consistent with the
  pre- or post-swap artifact, the other tenant untouched.
"""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.serve import ForestArena, ModelRegistry, PredictorSession, PredictServer


def _nan_matrix(rng, n, f_num, f_cat=0, cat_lo=-1, cat_hi=15):
    X = rng.normal(size=(n, f_num))
    X[rng.random((n, f_num)) < 0.08] = np.nan
    if f_cat:
        X = np.hstack([X, rng.integers(cat_lo, cat_hi, size=(n, f_cat)
                                       ).astype(np.float64)])
    return X


def _train(X, y, params, rounds, cat=None):
    p = dict({"verbose": -1, "num_leaves": 15, "min_data_in_leaf": 5},
             **params)
    ds = lgb.Dataset(X, label=y, params=p,
                     **({"categorical_feature": cat} if cat else {}))
    return lgb.train(p, ds, num_boost_round=rounds)


@pytest.fixture(scope="module")
def tenant_models():
    """(name, booster, probe matrix) triples spanning the binning
    surface: NaN-heavy binary, multiclass + categorical, dense binary —
    different feature counts on purpose (the arena widens to the union)."""
    rng = np.random.default_rng(10)
    Xb = _nan_matrix(rng, 600, 6)
    yb = (np.nan_to_num(Xb[:, 0]) + np.nan_to_num(Xb[:, 1]) > 0
          ).astype(np.float64)
    b_bin = _train(Xb, yb, {"objective": "binary"}, 10)

    Xm = _nan_matrix(rng, 600, 3, f_cat=1, cat_lo=0, cat_hi=12)
    ym = ((np.nan_to_num(Xm[:, 0]) > 0).astype(int)
          + (Xm[:, 3] > 5).astype(int)).astype(np.float64)
    b_mc = _train(Xm, ym, {"objective": "multiclass", "num_class": 3},
                  8, cat=[3])

    Xd = rng.normal(size=(600, 4))
    yd = (Xd[:, 0] - 0.5 * Xd[:, 2] > 0).astype(np.float64)
    b_dense = _train(Xd, yd, {"objective": "binary", "num_leaves": 7}, 12)

    probe = np.random.default_rng(11)
    return [("nan_bin", b_bin, _nan_matrix(probe, 160, 6)),
            ("mc_cat", b_mc,
             _nan_matrix(probe, 160, 3, f_cat=1, cat_lo=-2, cat_hi=20)),
            ("dense", b_dense, probe.normal(size=(160, 4)))]


# ---------------------------------------------------------------------------
# parity: one arena == N dedicated sessions, bit for bit
# ---------------------------------------------------------------------------

def test_arena_bit_identical_to_solo_sessions(tenant_models):
    arena = ForestArena(max_batch=64, max_wait_ms=1.0)
    try:
        for name, bst, _ in tenant_models:
            arena.admit(name, bst)
        for name, bst, Xt in tenant_models:
            with PredictorSession(bst, max_batch=64,
                                  max_wait_ms=1.0) as solo:
                # converted output, raw score, and the async route must
                # all be the SAME bits the dedicated session produces
                assert np.array_equal(arena.predict(Xt, model=name),
                                      solo.predict(Xt)), name
                assert np.array_equal(
                    arena.predict(Xt, model=name, raw_score=True),
                    solo.predict(Xt, raw_score=True)), name
                t = arena.submit(Xt[:48], model=name)
                assert np.array_equal(arena.result(t, timeout=60.0),
                                      solo.predict(Xt[:48])), name
        st = arena.stats()
        assert st["tenants"] == 3 and st["resident"] == 3
    finally:
        arena.close()


def test_arena_cross_model_coalescing(tenant_models):
    arena = ForestArena(max_batch=128, max_wait_ms=5.0)
    try:
        for name, bst, _ in tenant_models:
            arena.admit(name, bst)
        refs = {name: PredictorSession(bst, max_batch=128, max_wait_ms=1.0)
                for name, bst, _ in tenant_models}
        tickets = []
        for r in range(10):
            for name, _, Xt in tenant_models:
                tickets.append(
                    (name, Xt[r * 3:r * 3 + 3],
                     arena.submit(Xt[r * 3:r * 3 + 3], model=name)))
        for name, chunk, t in tickets:
            assert np.array_equal(arena.result(t, timeout=60.0),
                                  refs[name].predict(chunk)), name
        st = arena.stats()
        # 30 tiny submits must NOT mean 30 device dispatches: requests
        # for different tenants shared batches via the model-id lane
        assert st["cross_model_batches"] >= 1
        assert st["batches"] < len(tickets)
        for s in refs.values():
            s.close()
    finally:
        arena.close()


def test_arena_eviction_and_transparent_readmission(tenant_models):
    (n1, b1, X1), (n2, b2, _), _ = tenant_models
    arena = ForestArena(budget_bytes=1, max_batch=64, max_wait_ms=1.0)
    try:
        arena.admit(n1, b1)
        arena.admit(n2, b2)          # 1-byte budget: LRU n1 evicted
        st = arena.stats()
        assert st["evictions"] >= 1 and st["resident"] == 1
        assert arena.has(n1)         # still known, just not resident
        out = arena.predict(X1, model=n1)   # transparent re-admission
        assert arena.stats()["readmissions"] >= 1
        with PredictorSession(b1, max_batch=64, max_wait_ms=1.0) as solo:
            assert np.array_equal(out, solo.predict(X1))
    finally:
        arena.close()


# ---------------------------------------------------------------------------
# AOT: export -> deserialize -> serve, zero compiles, loud fallback
# ---------------------------------------------------------------------------

def test_aot_roundtrip_zero_compiles_request1_bounded(tenant_models,
                                                      tmp_path):
    name, bst, Xt = tenant_models[0]
    cfg = {"verbose": -1, "tpu_serve_aot_dir": str(tmp_path)}
    warm = PredictorSession(bst, max_batch=64, max_wait_ms=1.0, config=cfg)
    warm.warmup()
    sizes = (1, 2, 4, 8, 16, 32, 64)
    want = {n: warm.predict(Xt[:n]) for n in sizes}
    assert (warm.stats()["aot"] or {}).get("saved", 0) >= len(sizes)
    warm.close()

    obs.install_recompile_hook()
    c0 = obs.compile_count()
    cold = PredictorSession(bst, max_batch=64, max_wait_ms=1.0, config=cfg)
    t0 = time.perf_counter()
    first = cold.predict(Xt[:16])
    req1_ms = (time.perf_counter() - t0) * 1e3
    got = {n: cold.predict(Xt[:n]) for n in sizes}
    # the tentpole contract: a fresh session (fresh jit callable — any
    # non-AOT dispatch would have to compile) served the FULL pow2
    # sweep with ZERO compiles, bit-identically
    assert obs.compile_count() - c0 == 0
    assert np.array_equal(first, want[16])
    assert all(np.array_equal(want[n], got[n]) for n in sizes)
    st = cold.stats()["aot"]
    assert sorted(st["buckets"]) == sorted(sizes)
    # request #1 pays no hidden warm-up: steady p99 at the same bucket
    # bounds it (x2, with a small absolute floor for CI timer noise)
    lat = []
    for _ in range(30):
        t0 = time.perf_counter()
        cold.predict(Xt[:16])
        lat.append((time.perf_counter() - t0) * 1e3)
    from lightgbm_tpu.obs.report import percentile
    p99 = percentile(sorted(lat), 0.99)
    assert req1_ms <= max(2.0 * p99, 25.0), (req1_ms, p99)
    cold.close()


def test_aot_corrupt_entry_falls_back_loudly(tenant_models, tmp_path):
    name, bst, Xt = tenant_models[0]
    cfg = {"verbose": -1, "tpu_serve_aot_dir": str(tmp_path)}
    warm = PredictorSession(bst, max_batch=32, max_wait_ms=1.0, config=cfg)
    warm.warmup()
    warm.close()
    entries = [os.path.join(str(tmp_path), f)
               for f in os.listdir(str(tmp_path)) if f.endswith(".aot")]
    assert entries
    for p in entries:       # present but garbage
        with open(p, "r+b") as fh:
            fh.truncate(max(1, os.path.getsize(p) // 3))
    obs.enable_flight(64)
    sess = PredictorSession(bst, max_batch=32, max_wait_ms=1.0, config=cfg)
    out = sess.predict(Xt[:32])
    st = sess.stats()["aot"]
    # loud: counted in stats AND stamped into the post-mortem ring
    assert st["fallbacks"] >= 1 and not st["buckets"]
    assert any(e.get("event") == "aot_fallback"
               for e in obs.flight_snapshot())
    sess.close()
    # never wrong: the JIT fallback path is the same program
    with PredictorSession(bst, max_batch=32, max_wait_ms=1.0) as ref:
        assert np.array_equal(out, ref.predict(Xt[:32]))


# ---------------------------------------------------------------------------
# HTTP: concurrent mixed-tenant traffic + hot-swap of one tenant
# ---------------------------------------------------------------------------

def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_arena_http_mixed_tenants_hot_swap(tenant_models, tmp_path):
    (n1, b1, X1), (n2, b2, X2), _ = tenant_models
    # the swap target: a retrained variant of tenant 1 over the same
    # feature space
    rng = np.random.default_rng(12)
    Xr = _nan_matrix(rng, 500, 6)
    yr = (np.nan_to_num(Xr[:, 1]) > 0).astype(np.float64)
    b1v2 = _train(Xr, yr, {"objective": "binary", "num_leaves": 7}, 9)
    v2_path = str(tmp_path / "t1_v2.txt")
    b1v2.save_model(v2_path)

    reg = ModelRegistry(n_replicas=1, max_batch=64, max_wait_ms=1.0)
    reg.add_model("main", b2)
    arena = ForestArena(max_batch=64, max_wait_ms=1.0)
    arena.admit("t1", b1)
    arena.admit("t2", b2)
    reg.attach_arena(arena)

    probe1, probe2 = X1[:8], X2[:8]
    with PredictorSession(b1, max_batch=64, max_wait_ms=1.0) as s:
        ref1_old = s.predict(probe1)
    with PredictorSession(b1v2, max_batch=64, max_wait_ms=1.0) as s:
        ref1_new = s.predict(probe1)
    with PredictorSession(b2, max_batch=64, max_wait_ms=1.0) as s:
        ref2 = s.predict(probe2)

    with PredictServer(reg) as srv:
        u = srv.url
        errors, off_refs = [], []
        stop = threading.Event()
        lock = threading.Lock()

        def client(tenant, probe, refs):
            while not stop.is_set():
                s, body = _post(u + "/predict",
                                {"rows": probe.tolist(), "model": tenant})
                with lock:
                    if s != 200 or body.get("arena") is not True:
                        errors.append((tenant, s, body))
                        continue
                    got = np.asarray(body["predictions"])
                    # bit-consistent with SOME deployed version —
                    # mid-swap a response is old or new, never a blend
                    if not any(np.array_equal(got, r) for r in refs):
                        off_refs.append(tenant)

        threads = [
            threading.Thread(target=client,
                             args=("t1", probe1, [ref1_old, ref1_new])),
            threading.Thread(target=client, args=("t2", probe2, [ref2])),
            threading.Thread(target=client, args=("t2", probe2, [ref2])),
        ]
        for t in threads:
            t.start()
        # hot-swap tenant t1 mid-storm over the admin endpoint
        s, body = _post(u + "/models/t1/swap", {"model_file": v2_path})
        assert s == 200 and body.get("to_version") == 2, (s, body)
        stop.set()
        for t in threads:
            t.join(60)
        assert not errors, errors[:3]
        assert not off_refs, off_refs[:3]
        # post-swap: t1 serves the new artifact, t2 is untouched
        s, body = _post(u + "/predict",
                        {"rows": probe1.tolist(), "model": "t1"})
        assert s == 200
        assert np.array_equal(np.asarray(body["predictions"]), ref1_new)
        s, body = _post(u + "/predict",
                        {"rows": probe2.tolist(), "model": "t2"})
        assert s == 200
        assert np.array_equal(np.asarray(body["predictions"]), ref2)
    reg.close()
