"""Live training introspection plane (ISSUE 17): the train-side
/metrics + /progress + /debug/flight exporter (obs/board.py), the
per-rank straggler detector and measured-vs-model reconciliation
(obs/ranks.py), and their integration with the trainer.

The acceptance pin is the straggler CI-twin: this CPU container has no
cross-process collectives (jax 0.4.37), so the 2-process fault-injected
run is twinned single-process — the LOCAL rank is genuinely slowed by
the LGBM_TPU_FAULTS sleep harness while a monkeypatched
``train_stats_exchange`` supplies two synthetic fast peers.  The
detector must name this rank and the slowed phase, dump the flight
ring, and surface the skew on the live board.
"""
import json
import math
import threading
import time
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.obs import board, core, ranks
from lightgbm_tpu.obs.ranks import (PHASES, RankAggregator, Reconciler,
                                    StragglerDetector)
from lightgbm_tpu.robust import faults
from lightgbm_tpu.serve.metrics import parse_prometheus

_PARAMS = {"objective": "binary", "metric": "auc", "num_leaves": 7,
           "min_data_in_leaf": 5, "verbose": -1, "seed": 1}


@pytest.fixture(autouse=True)
def _clean_state():
    faults.disarm()
    yield
    faults.disarm()
    b = board.current()
    if b is not None:
        b.stop()


def _toy(n=600, f=8, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
    return X, y


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _feed_iterations(n, iter_s=0.1, start=0, **extra):
    for i in range(start, start + n):
        core.event("iteration", iteration=i, iter_s=iter_s,
                   metrics={"training.auc": 0.9}, recompiles=0,
                   phase_s={"tree growth": iter_s * 0.7,
                            "boosting (grad/hess)": iter_s * 0.2},
                   cum_row_iters_per_s=1e6, **extra)


# ---------------------------------------------------------------------------
# the exporter itself
# ---------------------------------------------------------------------------

def test_board_endpoints_and_shared_prometheus_reader():
    b = board.TrainBoard(total_rounds=10, port=0)
    b.start()
    try:
        assert board.active() and board.current() is b
        _feed_iterations(3)
        status, body = _get(b.url + "/metrics")
        assert status == 200
        parsed = parse_prometheus(body.decode())  # the serve-plane reader
        assert parsed["tpu_train_iteration"] == 2.0
        assert parsed["tpu_train_completed_iterations"] == 3.0
        assert parsed["tpu_train_total_rounds"] == 10.0
        assert parsed["tpu_train_row_iters_per_s"] == pytest.approx(1e6)
        status, body = _get(b.url + "/progress")
        pr = json.loads(body)
        assert pr["iteration"] == 2 and pr["total_rounds"] == 10
        assert len(pr["recent"]) == 3
        assert math.isfinite(pr["eta_s"]) and pr["eta_s"] > 0
        assert pr["vs_baseline"] is not None
        status, body = _get(b.url + "/debug/flight")
        fl = json.loads(body)
        assert fl["enabled"] and isinstance(fl["events"], list)
        with pytest.raises(urllib.error.HTTPError):
            _get(b.url + "/nope")
    finally:
        b.stop()
    assert not board.active()
    # unhooked: events after stop must not mutate the dead board
    it = b.progress()["iteration"]
    _feed_iterations(1, start=7)
    assert b.progress()["iteration"] == it


def test_eta_is_this_run_rate_not_wall_since_boot():
    """Satellite 6: a resumed board (start_round=80 of 100) fed 5
    iterations at 0.1s must report ETA ~= remaining * rate — NOT the
    naive uptime * total/completed extrapolation, which for a
    crash-resume would be wall-clock-since-boot scaled."""
    b = board.TrainBoard(total_rounds=100, start_round=80, port=0)
    b.start()
    try:
        _feed_iterations(5, iter_s=0.1, start=80)
        pr = b.progress()
        assert pr["start_round"] == 80
        assert pr["iteration"] == 84 and pr["completed"] == 5
        # remaining = 100 - (84+1) = 15 rounds at EMA 0.1s
        assert pr["eta_s"] == pytest.approx(1.5, rel=0.01)
        # the broken semantic would claim (100-85)/85 * uptime-ish
        # values or scale with the restored offset; pin the ceiling
        assert pr["eta_s"] < 5.0
        assert pr["frac"] == pytest.approx(0.85)
    finally:
        b.stop()


def test_resolve_port_env_and_config(monkeypatch):
    cfg = lgb.Config(tpu_train_metrics_port=8123)

    monkeypatch.delenv("LGBM_TPU_TRAIN_METRICS", raising=False)
    assert board.resolve_port(cfg) == 8123
    assert board.resolve_port(None) is None
    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "0")
    assert board.resolve_port(cfg) == 0
    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "off")
    assert board.resolve_port(cfg) is None
    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "-1")
    assert board.resolve_port(cfg) is None
    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "not-a-port")
    assert board.resolve_port(cfg) is None


def test_config_knob_validation():
    from lightgbm_tpu.utils.log import LightGBMError
    with pytest.raises(LightGBMError):
        lgb.Config.from_params({"tpu_train_metrics_port": 99999})
    with pytest.raises(LightGBMError):
        lgb.Config.from_params({"tpu_straggler_factor": 1.0})
    with pytest.raises(LightGBMError):
        lgb.Config.from_params({"tpu_straggler_iters": -1})


# ---------------------------------------------------------------------------
# straggler detector (pure streak logic)
# ---------------------------------------------------------------------------

def _mat(slow=0.01, fast=0.001, slow_rank=0, ranks=3):
    row_fast = [fast] * len(PHASES)
    rows = [list(row_fast) for _ in range(ranks)]
    rows[slow_rank] = [slow] * len(PHASES)
    return rows


def test_straggler_streak_emits_once_and_resets():
    det = StragglerDetector(factor=2.0, iters=3)
    # two windows of 1 iteration: streak 2 < 3 — silent
    assert det.update(_mat(), 1, iteration=1) == []
    assert det.update(_mat(), 1, iteration=2) == []
    # third consecutive: breach, naming rank and phase
    breaches = det.update(_mat(), 1, iteration=3)
    assert {b["rank"] for b in breaches} == {0}
    assert {b["phase"] for b in breaches} == set(PHASES)
    b = breaches[0]
    assert b["ratio"] == pytest.approx(10.0) and b["consecutive"] == 3
    assert b["breach"] is True
    # streak continues: already emitted, stays quiet
    assert det.update(_mat(), 1, iteration=4) == []
    # recovery resets the streak AND the emitted latch...
    assert det.update(_mat(slow=0.001), 1, iteration=5) == []
    # ...so a relapse emits again after another full streak
    assert det.update(_mat(), 3, iteration=8) != []


def test_straggler_window_iters_count_toward_streak():
    det = StragglerDetector(factor=2.0, iters=4)
    assert det.update(_mat(), 2, iteration=2) == []      # streak 2
    assert det.update(_mat(), 2, iteration=4) != []      # streak 4


def test_straggler_noise_floor_suppresses_microsecond_skew():
    det = StragglerDetector(factor=2.0, iters=1)
    # 10x skew over a 5us median is jitter, not a straggler
    assert det.update(_mat(slow=5e-5, fast=5e-6), 1, iteration=1) == []


def test_two_ranks_cannot_breach_factor_two():
    # with 2 ranks the median contains the straggler: wall > 2*median
    # is arithmetically impossible — documents why the CI twin
    # synthesizes a 3-rank fleet
    det = StragglerDetector(factor=2.0, iters=1)
    rows = [[0.1] * len(PHASES), [0.001] * len(PHASES)]
    assert det.update(rows, 1, iteration=1) == []


def test_rank_aggregator_single_process_is_noop():
    agg = RankAggregator(factor=2.0, iters=1)
    agg.accumulate({"tree growth": 0.1, "boosting (grad/hess)": 0.05})
    assert agg.exchange(iteration=1) is None   # no collective armed
    assert agg.exchange(iteration=2) is None   # empty window short-cuts


# ---------------------------------------------------------------------------
# reconciliation
# ---------------------------------------------------------------------------

def test_reconciler_scores_partition_and_growth():
    rec = Reconciler()
    units = rec.score(
        phase_s={"tree growth": 0.05, "boosting (grad/hess)": 0.01},
        iter_s=0.06, N=10_000, splits=6, part_batched=False)
    assert "partition" in units and "tree_growth" in units
    u = units["partition"]
    assert u["measured_s"] == pytest.approx(0.05)
    assert u["modeled_s"] > 0 and u["ratio"] > 0
    assert u["ratio"] == pytest.approx(u["measured_s"] / u["modeled_s"],
                                       rel=1e-3)


def test_reconciler_rank_pair_unit():
    rec = Reconciler()
    units = rec.score(
        phase_s={"tree growth": 0.05, "boosting (grad/hess)": 0.02},
        iter_s=0.07, N=3000, splits=0,
        rank_sizes=np.asarray([100, 200, 50], np.int64))
    assert set(units) == {"rank_pair"}
    assert units["rank_pair"]["measured_s"] == pytest.approx(0.02)


def test_reconciler_shap_unit():
    rec = Reconciler()
    u = rec.score_shap(0.5, N=1000, T=20, L=31, P=6, F=28, K=1)
    assert u["measured_s"] == pytest.approx(0.5) and u["modeled_s"] > 0


def test_reconciler_missing_inputs_yield_none():
    rec = Reconciler()
    assert rec.score(phase_s={}, iter_s=0.01, N=100, splits=0) is None


def test_train_emits_reconciliation_events(tmp_path):
    X, y = _toy()
    obs.enable(str(tmp_path / "telem"))
    try:
        ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
        lgb.train(dict(_PARAMS), ds, num_boost_round=6,
                  verbose_eval=False)
    finally:
        obs.disable()
    from lightgbm_tpu.obs.report import load_events, summarize
    events = load_events(str(tmp_path / "telem"))
    recs = [e for e in events if e.get("event") == "reconciliation"]
    assert recs, "steady-state iterations must score the cost models"
    units = recs[-1]["units"]
    assert "tree_growth" in units
    for u in units.values():
        assert u["modeled_s"] > 0 and u["ratio"] > 0
    digest = summarize(events)
    assert "tree_growth" in digest["reconciliation"]
    summary = digest["reconciliation"]["tree_growth"]
    assert summary["iterations"] == len(recs)
    assert summary["mean_ratio"] > 0


# ---------------------------------------------------------------------------
# the acceptance pin: fault-injected straggler, end to end
# ---------------------------------------------------------------------------

def test_straggler_acceptance_ci_twin(tmp_path, monkeypatch):
    """A rank slowed by the fault harness must produce: a ``straggler``
    event naming rank and phase, a flight dump, and live /metrics
    showing the skew.  Single-process twin of the 2-process run: the
    sleep fault makes THIS rank slow; the patched exchange supplies two
    synthetic fast peers (3-rank fleet — see the two-rank test above
    for why)."""
    import lightgbm_tpu.parallel.distributed as dist

    def fake_exchange(vec):
        # peers = this rank WITHOUT the injected sleep: identical
        # boosting wall, tree growth scaled way down — so the only
        # breach is in the faulted phase
        gi = PHASES.index("tree growth")
        peer = list(vec)
        peer[gi] = vec[gi] * 0.05
        return [list(vec), peer, list(peer)]

    monkeypatch.setattr(dist, "train_stats_exchange", fake_exchange)
    # every device execute sleeps 30ms — lands in "tree growth" wall
    faults.configure("device_execute:sleep=0.03@n=-1")

    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "0")
    telem = str(tmp_path / "telem")
    obs.enable(telem)

    seen = {"metrics": None, "skew": None}

    def poll():
        while not seen.get("stop"):
            b = board.current()
            if b is not None:
                try:
                    text = b.metrics_text()
                    if "tpu_train_stragglers_total 0" not in text \
                            and "tpu_train_stragglers_total" in text:
                        seen["metrics"] = text
                    if "tpu_train_phase_skew_seconds" in text:
                        seen["skew"] = text
                except Exception:
                    pass
            time.sleep(0.01)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    try:
        X, y = _toy()
        p = dict(_PARAMS, tpu_straggler_iters=2, tpu_straggler_factor=2.0,
                 tpu_fingerprint_freq=1)
        ds = lgb.Dataset(X, label=y, params=p)
        lgb.train(p, ds, num_boost_round=8, verbose_eval=False)
    finally:
        seen["stop"] = True
        obs.disable()
        faults.disarm()
    t.join(timeout=5)

    # 1. the straggler event names this rank and the slowed phase
    from lightgbm_tpu.obs.report import load_events
    stragglers = [e for e in load_events(telem)
                  if e.get("event") == "straggler"]
    assert stragglers, "slow rank must be reported"
    ev = stragglers[0]
    assert ev["rank"] == 0
    assert ev["phase"] == "tree growth"   # where the sleep fault lands
    assert ev["ratio"] > 2.0
    assert ev["consecutive"] >= 2

    # 2. the flight ring was dumped (conftest points FLIGHT_DIR at tmp)
    dumps = list(tmp_path.glob("FLIGHT_r*.json"))
    assert dumps, "a straggler breach must leave a post-mortem"
    dump = json.load(open(dumps[0]))
    assert dump.get("straggler", {}).get("rank") == 0
    assert "skew" in dump

    # 3. the live board showed the breach and the per-rank skew table
    assert seen["metrics"] is not None, "live /metrics never saw breach"
    parsed = parse_prometheus(seen["metrics"])
    assert parsed["tpu_train_stragglers_total"] >= 1.0
    assert seen["skew"] is not None
    assert 'rank="0"' in seen["skew"] and 'rank="1"' in seen["skew"]
    # the skew series carries the slowed phase for the slow rank
    assert 'tpu_train_phase_skew_seconds{rank="0",phase="tree growth"}' \
        in seen["skew"]


def test_straggler_detection_disabled_by_config(tmp_path):
    X, y = _toy(n=300)
    p = dict(_PARAMS, tpu_straggler_iters=0)
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, num_boost_round=2, verbose_eval=False)
    assert bst._gbdt._ranks is None


# ---------------------------------------------------------------------------
# engine integration: arming, resume anchoring, teardown
# ---------------------------------------------------------------------------

def test_engine_arms_board_and_stops_after_train(monkeypatch):
    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "0")
    snaps = []

    def poll():
        while not snaps or snaps[-1] != "stop":
            b = board.current()
            if b is not None:
                try:
                    snaps.append(b.progress())
                except Exception:
                    pass
            time.sleep(0.005)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    X, y = _toy(n=2000)
    ds = lgb.Dataset(X, label=y, params=dict(_PARAMS))
    lgb.train(dict(_PARAMS), ds, num_boost_round=6, verbose_eval=False)
    snaps.append("stop")
    t.join(timeout=5)
    assert not board.active(), "engine must tear the exporter down"
    prs = [s for s in snaps if isinstance(s, dict)]
    assert prs, "board was never live during the train"
    assert any(p["total_rounds"] == 6 for p in prs)
    ws = [p for p in prs if p.get("watchdog")]
    assert ws and "active" in ws[0]["watchdog"]


def test_progress_resume_anchoring_end_to_end(tmp_path, monkeypatch):
    """Crash at 4, resume to 10 with the exporter armed: /progress must
    anchor at the restored iteration (start_round=4) with this-run ETA
    — satellite 6's regression pin at the engine level."""
    X, y = _toy(n=2000)
    ck = str(tmp_path / "ck")
    p = dict(_PARAMS, tpu_checkpoint_dir=ck, tpu_checkpoint_freq=2)
    ds = lgb.Dataset(X, label=y, params=dict(p))
    lgb.train(dict(p), ds, num_boost_round=4, verbose_eval=False)

    monkeypatch.setenv("LGBM_TPU_TRAIN_METRICS", "0")
    prs = []

    def poll():
        while not prs or prs[-1] != "stop":
            b = board.current()
            if b is not None:
                try:
                    prs.append(b.progress())
                except Exception:
                    pass
            time.sleep(0.005)

    t = threading.Thread(target=poll, daemon=True)
    t.start()
    ds = lgb.Dataset(X, label=y, params=dict(p))
    lgb.train(dict(p), ds, num_boost_round=10, verbose_eval=False)
    prs.append("stop")
    t.join(timeout=5)
    snaps = [s for s in prs if isinstance(s, dict)]
    assert snaps, "board never scraped during resume"
    assert all(s["start_round"] == 4 for s in snaps)
    assert all(s["total_rounds"] == 10 for s in snaps)
    late = [s for s in snaps if s["iteration"] is not None
            and s["completed"] >= 2]
    assert late, "no snapshot after the rate estimate settled"
    for s in late:
        # this-run rate: remaining * EMA, NOT uptime-extrapolated
        remaining = s["total_rounds"] - (s["iteration"] + 1)
        # progress() rounds eta_s to 3 decimals
        assert s["eta_s"] == pytest.approx(
            s["ema_iter_s"] * remaining, abs=1e-3)
        # iteration numbering is global (resumed at 4)
        assert s["iteration"] >= 4


# ---------------------------------------------------------------------------
# report plane: straggler/reconciliation digest + CLI entry
# ---------------------------------------------------------------------------

def test_report_digest_renders_straggler_and_reconciliation():
    from lightgbm_tpu.obs.report import render, summarize
    events = [
        {"event": "straggler", "t": 1.0, "rank": 2, "phase": "tree growth",
         "iteration": 10, "ratio": 3.2, "median_s": 0.01, "rank_s": 0.032,
         "consecutive": 3, "breach": True, "_proc": 0},
        {"event": "reconciliation", "t": 2.0, "iteration": 11,
         "units": {"wave_kernel": {"measured_s": 0.02, "modeled_s": 0.01,
                                   "ratio": 2.0}}, "_proc": 0},
        {"event": "reconciliation", "t": 3.0, "iteration": 12,
         "units": {"wave_kernel": {"measured_s": 0.04, "modeled_s": 0.01,
                                   "ratio": 4.0}}, "_proc": 0},
    ]
    digest = summarize(events)
    assert digest["stragglers"][0]["rank"] == 2
    wk = digest["reconciliation"]["wave_kernel"]
    assert wk["iterations"] == 2
    assert wk["mean_ratio"] == pytest.approx(3.0)
    assert wk["worst_ratio"] == pytest.approx(4.0)
    assert wk["worst_iteration"] == 12
    text = render(digest)
    assert "straggler" in text.lower()
    assert "wave_kernel" in text


def test_report_cli_module_entry(tmp_path):
    import subprocess
    import sys
    d = tmp_path / "telem"
    d.mkdir()
    (d / "telemetry.0.jsonl").write_text(json.dumps(
        {"event": "iteration", "t": 1.0, "iteration": 0, "iter_s": 0.1,
         "phase_s": {}, "metrics": {}}) + "\n")
    r = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu.obs.report", str(d),
         "--json"], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    digest = json.loads(r.stdout)
    assert digest["iterations"] == 1
    # the deprecated shim still answers
    import os
    shim = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "telemetry_report.py")
    r = subprocess.run([sys.executable, shim, str(d), "--json"],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["iterations"] == 1
    assert "shim" in r.stderr


# ---------------------------------------------------------------------------
# train_watch formatting (pure)
# ---------------------------------------------------------------------------

def test_train_watch_format_iteration():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    from train_watch import format_iteration
    line = format_iteration(
        {"iteration": 42, "iter_s": 0.213, "cum_row_iters_per_s": 1.23e7,
         "metrics": {"valid_0.auc": 0.9312}, "recompiles": 0}, total=500)
    assert "42/500" in line and "0.213s" in line
    assert "1.23e+07" in line and "valid_0.auc=0.9312" in line
    assert "recompiled" not in line
    line = format_iteration({"iteration": 3, "iter_s": 1.0,
                             "recompiles": 2})
    assert "[recompiled]" in line
    # None-safe on sparse records
    assert format_iteration({}) .startswith("iter")
