"""CSR-native dataset construction (no dense materialization).

The reference ingests wide sparse data via SparseBin delta-encoded streams
(src/io/sparse_bin.hpp:72, ordered_sparse_bin.hpp:1); this framework bins
stored entries column-by-column from CSC, packs mutually-exclusive
features with EFB (uint16-wide bundle columns past 2048 features), and
histograms wide layouts with the scatter-add path instead of one-hot.
"""
import numpy as np
import pytest

scipy_sparse = pytest.importorskip("scipy.sparse")

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.io.dataset import BinnedDataset


def _sparse_problem(n=400, f=30, density=0.15, seed=3):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    X[rng.random((n, f)) > density] = 0.0
    y = (X[:, 0] - X[:, 1] + 0.2 * rng.normal(size=n) > 0).astype(float)
    return scipy_sparse.csr_matrix(X), X, y


def test_from_csr_matches_dense():
    """from_csr must produce the EXACT dataset from_matrix builds on the
    densified values (stored-entry binning == dense-column binning)."""
    Xs, Xd, _ = _sparse_problem()
    cfg = Config.from_params({"verbose": -1, "max_bin": 31})
    h1 = BinnedDataset.from_matrix(Xd, cfg)
    h2 = BinnedDataset.from_csr(Xs, cfg)
    assert h2.num_data == h1.num_data
    np.testing.assert_array_equal(h2.bin_offsets, h1.bin_offsets)
    np.testing.assert_array_equal(h2.X_bin, h1.X_bin)
    assert (h2.bundle is None) == (h1.bundle is None)
    # valid alignment path too
    h3 = BinnedDataset.from_csr(Xs, cfg, reference=h1)
    np.testing.assert_array_equal(h3.X_bin, h1.X_bin)


def test_sparse_train_matches_dense():
    """lgb.train on a scipy CSR matrix == training on its dense copy."""
    Xs, Xd, y = _sparse_problem(n=600)
    params = {"objective": "binary", "num_leaves": 15, "max_bin": 31,
              "min_data_in_leaf": 5, "verbose": -1}
    b1 = lgb.train(params, lgb.Dataset(Xd, label=y), num_boost_round=10)
    b2 = lgb.train(params, lgb.Dataset(Xs, label=y), num_boost_round=10)
    p1 = b1.predict(Xd)
    p2 = b2.predict(Xs)
    np.testing.assert_allclose(p1, p2, rtol=1e-6, atol=1e-9)
    # AUC sanity
    order = np.argsort(p2)
    ranks = np.empty_like(order, float)
    ranks[order] = np.arange(len(y))
    pos, neg = ranks[y == 1], ranks[y == 0]
    auc = (pos.mean() - neg.mean()) / len(y) + 0.5
    assert auc > 0.7


def test_wide_sparse_constructs_and_trains():
    """A genuinely wide sparse dataset (the scaled-down acceptance shape:
    the full 1M x 50k drive lives in the verify skill) constructs without
    densifying and trains through the scatter-histogram path."""
    rng = np.random.default_rng(0)
    n, f, nnz_per_row = 20000, 5000, 8
    rows = np.repeat(np.arange(n), nnz_per_row)
    cols = rng.integers(0, f, size=n * nnz_per_row)
    # values correlated with a hidden subset of columns for learnability
    informative = cols < 50
    vals = np.where(informative, 1.0 + rng.random(n * nnz_per_row),
                    rng.normal(size=n * nnz_per_row))
    X = scipy_sparse.csr_matrix((vals, (rows, cols)), shape=(n, f))
    row_signal = np.zeros(n)
    np.add.at(row_signal, rows[informative], vals[informative])
    y = (row_signal + 0.5 * rng.normal(size=n) > 1.0).astype(float)

    params = {"objective": "binary", "num_leaves": 7, "max_bin": 15,
              "min_data_in_leaf": 20, "max_conflict_rate": 0.1,
              "verbose": -1}
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    h = ds._handle
    # EFB must have packed the 5k features into far fewer physical columns
    assert h.bundle is not None
    assert h.num_phys_features < f // 4, h.num_phys_features
    bst = lgb.train(params, ds, num_boost_round=3)
    pred = bst.predict(X)
    assert pred.shape == (n,)
    # better than chance on the informative signal
    auc_num = (pred[y == 1].mean() - pred[y == 0].mean())
    assert auc_num > 0.01, auc_num
