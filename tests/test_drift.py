"""Model-quality & drift plane (ISSUE 16): reference profiles,
streaming sketches, rolling quality, breach wiring.

The contracts under test:

- ``save_model`` writes the ``<model>.quality.json`` sidecar whose
  per-feature occupancy matches the serve-side ``bin_features`` binning
  exactly (bin-space consistency — PSI must measure traffic shift,
  never binning skew), and whose chunked/streamed accumulation equals
  the one-shot scan;
- PSI/KS/coarsen behave (zero on identity, monotone under shift,
  coarsening preserves mass) and the prediction histogram's tie-robust
  edges survive float-noise-level score perturbation;
- per-replica ``DriftSketch`` merge is bit-exact against the
  single-accumulator oracle;
- a ``DriftMonitor`` fed i.i.d. training-like traffic stays quiet while
  seeded covariate shift breaches, dumps the flight recorder, and
  latches the breach the registry's post-swap watch reads (rollback on
  the ``tpu_serve_rollback_on_drift`` opt-in only);
- the serve surfaces expose it all: ``stats()['drift']``,
  ``tpu_serve_drift_*`` + ``tpu_serve_resident_bytes`` in /metrics,
  GET /drift, the online-loop counters in the fleet exposition, and
  the ``drift_snapshot``/``quality_window`` events validate against
  their schemas and fold into ``drift_summary``.

All CPU-runnable, quick tier.
"""
import json
import os
import urllib.request

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu import obs
from lightgbm_tpu.config import Config
from lightgbm_tpu.obs.drift import (DriftMonitor, DriftSketch,
                                    FEAT_PSI_BUCKETS, QualityProfile,
                                    _pred_histogram, bin_features, coarsen,
                                    compute_occupancy, ks, profile_path,
                                    psi)
from lightgbm_tpu.obs.report import (drift_summary, load_events,
                                     validate_events)
from lightgbm_tpu.serve import (ModelRegistry, PredictorSession,
                                PredictServer, parse_prometheus)
from lightgbm_tpu.serve.metrics import (render_prometheus,
                                        render_prometheus_fleet)
from lightgbm_tpu.serve.quality import QualityTracker

P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
     "verbose": -1}


@pytest.fixture(autouse=True)
def _obs_clean():
    yield
    obs.disable()
    obs.enable_flight(0)
    obs.reset()


@pytest.fixture(scope="module")
def drift_model(tmp_path_factory):
    """One trained binary model saved to a file (sidecar rides along),
    plus its training matrix — the reference distribution."""
    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 6))
    X[rng.random(X.shape) < 0.03] = np.nan
    y = (np.nan_to_num(X[:, 0]) + 0.5 * np.nan_to_num(X[:, 1]) > 0
         ).astype(np.float64)
    bst = lgb.train(P, lgb.Dataset(X, label=y, params=P),
                    num_boost_round=8)
    path = str(tmp_path_factory.mktemp("drift") / "model.txt")
    bst.save_model(path)
    return path, bst, X, y


def _cfg(**over):
    base = dict(P, tpu_serve_max_batch=64, tpu_serve_max_wait_ms=1.0,
                tpu_serve_canary_rows=16, tpu_serve_canary_probes=2,
                tpu_serve_rollback_watch_s=0.0, tpu_serve_reprobe_s=0.0,
                tpu_drift_sample_rate=1.0, tpu_drift_min_rows=64)
    base.update(over)
    return Config.from_params(base)


def _shifted(n=256, seed=11):
    rng = np.random.default_rng(seed)
    return rng.normal(size=(n, 6)) * 2.5 + 1.5


# ---------------------------------------------------------------------
# reference profile: sidecar capture + bin-space consistency
# ---------------------------------------------------------------------

def test_profile_sidecar_written_and_roundtrips(drift_model):
    path, _, X, _ = drift_model
    side = profile_path(path)
    assert os.path.isfile(side)
    prof = QualityProfile.load(side)
    assert len(prof.features) == X.shape[1]
    for rec in prof.features:
        assert sum(rec["counts"]) == X.shape[0]
        assert not rec["categorical"]
    assert sum(prof.pred["counts"]) == X.shape[0]
    assert len(prof.pred["counts"]) == len(prof.pred["edges"]) + 1
    assert prof.meta["rows"] == X.shape[0]
    assert 0.5 < prof.meta["train_auc"] <= 1.0
    # dict round-trip is lossless (registry carries profiles as dicts)
    again = QualityProfile.from_dict(prof.to_dict())
    assert again.to_dict() == prof.to_dict()


def test_bin_features_matches_training_occupancy(drift_model):
    """Serve-side binning of the raw training rows reproduces the
    profile's occupancy exactly — the bin-space-consistency invariant
    that keeps PSI free of binning skew."""
    path, _, X, _ = drift_model
    prof = QualityProfile.load(profile_path(path))
    recs = prof.numeric_records()
    assert recs, "all-dense numeric features must all profile"
    bins = bin_features(X, recs)
    for rec, b in zip(recs, bins):
        got = np.bincount(b, minlength=rec["num_bin"])
        assert np.array_equal(got, np.asarray(rec["counts"])), rec["name"]


def test_occupancy_chunked_matches_one_shot(drift_model):
    """Streaming ingestion accumulates occupancy chunk by chunk during
    pass 2 — any chunking must equal the whole-matrix scan."""
    _, bst, _, _ = drift_model
    ds = bst._gbdt.train_ds
    full = compute_occupancy(ds, chunk_rows=1 << 20)
    for chunk in (37, 128):
        acc = compute_occupancy(ds, chunk_rows=chunk)
        for a, b in zip(acc, full):
            assert np.array_equal(a, b)


# ---------------------------------------------------------------------
# distances: psi / ks / coarsen / tie-robust prediction edges
# ---------------------------------------------------------------------

def test_psi_ks_basic_properties():
    p = np.array([10, 20, 30, 40], float)
    assert psi(p, p) == 0.0
    assert ks(p, p) == 0.0
    assert psi(p, [0, 0, 0, 0]) == 0.0       # degenerate -> neutral
    near = [11, 19, 31, 39]
    far = [40, 30, 20, 10]
    assert 0.0 < psi(p, near) < psi(p, far)
    assert 0.0 < ks(p, near) < ks(p, far) <= 1.0


def test_coarsen_equal_reference_mass():
    rng = np.random.default_rng(0)
    ref = rng.integers(0, 50, size=255).astype(np.int64)
    live = rng.integers(0, 5, size=255).astype(np.int64)
    rc, lc = coarsen(ref, live)
    assert len(rc) <= FEAT_PSI_BUCKETS + 1 and len(rc) == len(lc)
    assert rc.sum() == ref.sum() and lc.sum() == live.sum()
    # identical distributions stay identical after regrouping
    rc2, lc2 = coarsen(ref, ref * 3)
    assert psi(rc2, lc2) < 1e-12
    # already-coarse histograms pass through untouched
    small = np.arange(8, dtype=float)
    a, b = coarsen(small, small)
    assert np.array_equal(a, small) and np.array_equal(b, small)


def test_coarsen_absorbs_sparse_sample_noise():
    """The motivating failure: a thin i.i.d. sample over many fine bins
    leaves most bins empty, and epsilon smoothing reads each as a large
    PSI term.  Coarse view must stay well under the default 0.25 warn
    while the fine view blows past it."""
    rng = np.random.default_rng(1)
    ref_vals = rng.normal(size=200_000)
    live_vals = rng.normal(size=400)          # thin but same distribution
    edges = np.quantile(ref_vals, np.linspace(0, 1, 256)[1:-1])
    ref = np.bincount(np.searchsorted(edges, ref_vals), minlength=256)
    live = np.bincount(np.searchsorted(edges, live_vals), minlength=256)
    assert psi(ref, live) > 0.25               # fine bins: false alarm
    rc, lc = coarsen(ref, live)
    assert psi(rc, lc) < 0.1                   # coarse: quiet


def test_pred_histogram_tie_robust_edges():
    """GBDT margins are discrete; serve-time recomputation differs from
    the training accumulation by float noise.  Edges must sit BETWEEN
    distinct values so a 1e-9 wobble never flips a tie clump."""
    rng = np.random.default_rng(2)
    vals = np.array([-1.2, -0.4, 0.1, 0.9, 2.0])
    s = rng.choice(vals, size=500)
    edges, counts = _pred_histogram(s)
    assert sum(counts) == s.size
    assert not np.isin(np.asarray(edges), vals).any()
    jittered = s + rng.uniform(-1e-9, 1e-9, size=s.size)
    binned = np.bincount(np.searchsorted(edges, jittered, side="left"),
                         minlength=len(counts))
    assert np.array_equal(binned, counts)
    # degenerate streams don't fabricate edges
    assert _pred_histogram(np.full(9, 3.0)) == ([], [9])
    assert _pred_histogram(np.array([])) == ([], [0])


# ---------------------------------------------------------------------
# sketch: replica merge bit-exactness
# ---------------------------------------------------------------------

def test_sketch_merge_matches_single_accumulator_oracle(drift_model):
    path, bst, X, _ = drift_model
    prof = QualityProfile.load(profile_path(path))
    scores = bst.predict(X, raw_score=True)
    a, b, oracle = (DriftSketch(prof) for _ in range(3))
    a.observe_features(X[:220]); a.observe_preds(scores[:220])
    b.observe_features(X[220:]); b.observe_preds(scores[220:])
    oracle.observe_features(X); oracle.observe_preds(scores)
    a.merge(b)
    sa, so = a.snapshot(), oracle.snapshot()
    assert sa["feat_rows"] == so["feat_rows"]
    assert sa["pred_rows"] == so["pred_rows"]
    assert np.array_equal(sa["pred_counts"], so["pred_counts"])
    for ca, co in zip(sa["feat_counts"], so["feat_counts"]):
        assert np.array_equal(ca, co)


# ---------------------------------------------------------------------
# monitor: differential (iid quiet / shift breaches), knobs, latch
# ---------------------------------------------------------------------

def test_monitor_iid_quiet_shift_breaches(drift_model, tmp_path,
                                          monkeypatch):
    path, bst, X, _ = drift_model
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))
    obs.enable_flight(64)
    prof = QualityProfile.load(profile_path(path))

    quiet = DriftMonitor(prof, _cfg())
    quiet.observe(X, bst.predict(X, raw_score=True))
    sq = quiet.maybe_check(force=True)
    assert sq["feat_rows"] == len(X)
    assert sq["psi_max"] < quiet.psi_warn
    assert sq["pred_psi"] < quiet.psi_warn
    assert quiet.breach is None

    mon = DriftMonitor(prof, _cfg())
    Xs = _shifted(256)
    mon.observe(Xs, bst.predict(Xs, raw_score=True))
    s = mon.maybe_check(force=True)
    assert s["psi_max"] > mon.psi_warn
    assert mon.breach is not None
    assert "feature_psi" in mon.breach["kinds"]
    assert mon.breach_count == 1
    dumps = list(tmp_path.glob("FLIGHT_r*.json"))
    assert dumps, "a drift breach must dump the flight recorder"
    rec = json.loads(dumps[0].read_text())
    assert rec["reason"].startswith("drift_psi:")
    assert "feature_psi" in rec["breach"]["kinds"]
    st = mon.status()
    assert st["armed"] and st["breaches"] == 1
    assert st["scores"]["psi_max"] == s["psi_max"]
    assert "per_feature" not in st["scores"]


def test_monitor_arming_and_kill_switch(drift_model, tmp_path,
                                        monkeypatch):
    path, bst, _, _ = drift_model
    assert DriftMonitor.maybe_load(path, _cfg()) is not None
    # env knobs override config (the LGBM_TPU_ prefix folds tpu_ in)
    monkeypatch.setenv("LGBM_TPU_DRIFT_SAMPLE_RATE", "0.5")
    assert DriftMonitor.maybe_load(path, _cfg()).sample_rate == 0.5
    monkeypatch.setenv("LGBM_TPU_DRIFT", "0")
    assert DriftMonitor.maybe_load(path, _cfg()) is None
    monkeypatch.delenv("LGBM_TPU_DRIFT")
    # in-memory models have no sidecar to find
    assert DriftMonitor.maybe_load(bst, _cfg()) is None
    # missing or corrupt sidecar: serve on, monitoring off
    lone = tmp_path / "bare.txt"
    lone.write_text("tree\n")
    assert DriftMonitor.maybe_load(str(lone), _cfg()) is None
    (tmp_path / "bare.txt.quality.json").write_text("{not json")
    assert DriftMonitor.maybe_load(str(lone), _cfg()) is None


def test_monitor_sampler_rate_honored(drift_model):
    path, _, X, _ = drift_model
    prof = QualityProfile.load(profile_path(path))
    mon = DriftMonitor(prof, _cfg(tpu_drift_sample_rate=0.25))
    for s in range(0, 512, 32):             # 16 batches of 32
        mon.observe(X[:32], np.zeros(32))
    st = mon.status()                        # drains the pending buffer
    assert st["pred_rows"] == 512            # predictions: every row
    assert st["feat_rows"] == 128            # features: exactly 1 in 4


# ---------------------------------------------------------------------
# serve surfaces: session stats, /metrics, /drift, registry annotation
# ---------------------------------------------------------------------

def test_session_drift_stats_and_prometheus(drift_model):
    path, _, X, _ = drift_model
    sess = PredictorSession(path, max_batch=64, max_wait_ms=0.5,
                            config=_cfg())
    try:
        for s in range(0, 256, 64):
            sess.predict(X[s:s + 64])
        sess._drift.maybe_check(force=True)
        st = sess.stats()
        dr = st["drift"]
        assert dr["armed"] and dr["feat_rows"] >= 256
        assert dr["pred_rows"] >= 256
        assert st["resident_bytes"] > 0
        text = render_prometheus(sess)
        parsed = parse_prometheus(text)
        key = ('tpu_serve_drift_score{model="default",version="0",'
               'kind="psi_max"}')
        assert parsed[key] == dr["scores"]["psi_max"]
        assert parsed['tpu_serve_drift_rows{model="default",version="0",'
                      'kind="pred"}'] == dr["pred_rows"]
        assert parsed['tpu_serve_drift_breach{model="default",'
                      'version="0"}'] == 0.0
        assert parsed["tpu_serve_resident_bytes"] == st["resident_bytes"]
    finally:
        sess.close()


def test_session_drift_disabled_by_config(drift_model):
    path, _, X, _ = drift_model
    sess = PredictorSession(path, max_batch=64, max_wait_ms=0.5,
                            config=_cfg(tpu_drift=False))
    try:
        sess.predict(X[:8])
        assert sess.stats()["drift"] is None
        assert "tpu_serve_drift_score" not in render_prometheus(sess)
    finally:
        sess.close()


def test_registry_drift_endpoint_and_fleet_metrics(drift_model):
    path, _, X, _ = drift_model
    reg = ModelRegistry(config=_cfg(), n_replicas=1)
    server = None
    try:
        reg.add_model("default", path)
        for s in range(0, len(X), 120):
            t = reg.submit(X[s:s + 120])
            reg.result(t, timeout=30)
        mon = reg.resolve(None).router.drift
        assert mon is not None and mon.model_version == 1
        mon.maybe_check(force=True)
        row = reg.models()[0]
        assert row["drift"]["armed"] and row["drift"]["breach"] is None
        assert row["resident_bytes"] > 0

        # fleet exposition: per-version residency + online-loop counters
        reg.online_provider = lambda: {
            "versions": 3, "rejected": 1, "failed": 0, "skipped": 2,
            "rows_ingested": 640, "last_refresh_age_s": 1.5}
        parsed = parse_prometheus(render_prometheus_fleet(reg))
        assert parsed['tpu_serve_drift_breach{model="default",'
                      'version="1"}'] == 0.0
        assert parsed['tpu_serve_resident_bytes{model="default",'
                      'version="1"}'] > 0
        assert parsed['tpu_online_refresh_total{outcome="pushed"}'] == 3.0
        assert parsed['tpu_online_refresh_total{outcome="rejected"}'] == 1.0
        assert parsed["tpu_online_swap_rejected_total"] == 1.0
        assert parsed["tpu_online_rows_ingested_total"] == 640.0
        assert parsed["tpu_online_last_refresh_age_seconds"] == 1.5

        # GET /drift over HTTP mirrors the registry's per-model status
        server = PredictServer(reg).start()
        with urllib.request.urlopen(server.url + "/drift",
                                    timeout=30) as r:
            body = json.loads(r.read())
        assert body["models"]["default"]["drift"]["armed"]
        assert body["models"]["default"]["quality_breach"] is None
    finally:
        if server is not None:
            server.stop()
        reg.close()


def test_postswap_annotates_default_and_rolls_back_on_optin(drift_model,
                                                            tmp_path):
    """A latched breach annotates the watch verdict by default; the
    tpu_serve_rollback_on_drift opt-in turns the same latch into an
    automatic rollback."""
    path, bst, X, _ = drift_model
    # a second version to swap to (its own sidecar rides along)
    p2 = dict(P, learning_rate=0.2)
    b2 = lgb.train(p2, lgb.Dataset(X, label=(np.nan_to_num(X[:, 0]) > 0
                                             ).astype(float), params=p2),
                   num_boost_round=4)
    m2 = str(tmp_path / "m2.txt")
    b2.save_model(m2)

    for optin in (False, True):
        reg = ModelRegistry(config=_cfg(
            tpu_serve_rollback_on_drift=optin), n_replicas=1)
        try:
            reg.add_model("default", path)
            reg.swap("default", m2)
            mon = reg.resolve(None).router.drift
            mon.observe(_shifted(256), np.zeros(256))
            assert mon.maybe_check(force=True)["psi_max"] > mon.psi_warn
            rep = reg.check_postswap("default")
            if optin:
                assert rep["reason"].startswith("auto: drift_psi")
                assert rep["to_version"] == 1
            else:
                assert rep["status"] in ("watching", "clear")
                assert "feature_psi" in rep["drift_breach"]["kinds"]
                assert reg.models()[0]["live_version"] == 2
        finally:
            reg.close()


# ---------------------------------------------------------------------
# rolling label quality (serve/quality.py) + registry latch
# ---------------------------------------------------------------------

def test_quality_tracker_windows_and_breach(drift_model):
    path, bst, X, y = drift_model
    prof = QualityProfile.load(profile_path(path))

    class _Latch:
        note = None

        def note_quality_breach(self, name, info):
            self.note = (name, dict(info))

    latch = _Latch()
    tr = QualityTracker(lambda rows: bst.predict(rows, raw_score=True),
                        prof, config=_cfg(tpu_quality_window=200),
                        registry=latch, model_name="default")
    tr.add(X[:150], y[:150])                 # below the window: buffered
    assert tr.windows == 0 and tr.stats()["buffered"] == 150
    tr.add(X[150:300], y[150:300])
    assert tr.windows == 1
    assert tr.last["auc"] > 0.8 and not tr.last["breach"]
    assert latch.note is None
    # flipped labels crater windowed AUC past the drop threshold
    tr.add(X[300:500], 1.0 - y[300:500])
    assert tr.windows == 2 and tr.last["breach"]
    assert tr.last["auc_delta"] > tr.drop_warn
    assert tr.breaches == 1
    assert latch.note[0] == "default"
    assert latch.note[1]["auc_delta"] == tr.last["auc_delta"]


def test_online_loop_carries_quality_and_refresh_age(drift_model,
                                                     tmp_path):
    from lightgbm_tpu.online.loop import OnlineLoop
    path, bst, X, y = drift_model
    prof = QualityProfile.load(profile_path(path))
    loop = OnlineLoop(path, config=_cfg(), workdir=str(tmp_path))
    loop.quality = QualityTracker(
        lambda rows: bst.predict(rows, raw_score=True), prof,
        config=_cfg(tpu_quality_window=128))
    loop.ingest(X[:256], y[:256])
    st = loop.stats()
    assert st["rows_ingested"] == 256
    assert st["last_refresh_age_s"] >= 0.0
    assert st["quality"]["windows"] == 2
    assert st["quality"]["last"]["auc"] > 0.8


# ---------------------------------------------------------------------
# telemetry: event schemas + digest section
# ---------------------------------------------------------------------

def test_drift_events_validate_and_summarize(drift_model, tmp_path):
    path, bst, X, y = drift_model
    obs.enable(str(tmp_path / "telem"))
    try:
        prof = QualityProfile.load(profile_path(path))
        mon = DriftMonitor(prof, _cfg())
        mon.observe(X, bst.predict(X, raw_score=True))
        mon.maybe_check(force=True)          # quiet snapshot
        Xs = _shifted(2048)
        mon.observe(Xs, bst.predict(Xs, raw_score=True))
        mon.maybe_check(force=True)          # breaching snapshot
        tr = QualityTracker(lambda rows: bst.predict(rows,
                                                     raw_score=True),
                            prof, config=_cfg(tpu_quality_window=200))
        tr.add(X[:200], y[:200])
    finally:
        obs.disable()
    events = load_events(str(tmp_path / "telem"))
    assert validate_events(events) == []
    d = drift_summary(events)
    assert d["snapshots"] == 2 and d["drift_breaches"] == 1
    assert d["quality_windows"] == 1 and d["quality_breaches"] == 0
    assert d["psi_max"] > 0.25
    assert d["last_snapshot"]["breach"] is True
    assert d["last_window"]["auc"] > 0.8


# ---------------------------------------------------------------------
# parse_prometheus: labeled series (the bench/test shared parser)
# ---------------------------------------------------------------------

def test_parse_prometheus_labeled_series():
    text = "\n".join([
        "# HELP tpu_serve_drift_score Live-traffic drift.",
        "# TYPE tpu_serve_drift_score gauge",
        'tpu_serve_drift_score{model="a b",version="1",kind="psi_max"}'
        " 0.125",
        'tpu_serve_drift_score{model="a b",version="1",kind="ks_max"}'
        " 0.5",
        "tpu_serve_resident_bytes 4096",
        "tpu_serve_request_latency_ms_sum 12.5",
        "",
        "not a metric line at all with trailing junk words",
        "tpu_bad_value{x=\"1\"} notanumber",
    ])
    parsed = parse_prometheus(text)
    assert parsed['tpu_serve_drift_score{model="a b",version="1",'
                  'kind="psi_max"}'] == 0.125
    assert parsed['tpu_serve_drift_score{model="a b",version="1",'
                  'kind="ks_max"}'] == 0.5
    assert parsed["tpu_serve_resident_bytes"] == 4096.0
    assert parsed["tpu_serve_request_latency_ms_sum"] == 12.5
    assert not any("bad_value" in k or "junk" in k for k in parsed)
