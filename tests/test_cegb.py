"""CEGB (cost-effective gradient boosting) tests.

Mirrors the reference's CEGB behavior checks (reference:
tests/python_package_test/test_basic.py:236-300,
src/treelearner/cost_effective_gradient_boosting.hpp:21-117).
"""
import numpy as np
import pytest

import lightgbm_tpu as lgb


def _data(seed=0, n=1500, f=8):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, f))
    # every feature mildly informative so penalties change the choice set
    w = rng.normal(size=f) * 0.6
    y = (X @ w + rng.logistic(size=n) * 0.5 > 0).astype(np.float64)
    return X, y


def _features_used(bst):
    return {i for i, v in enumerate(bst.feature_importance("split")) if v > 0}


def test_coupled_penalty_narrows_feature_set():
    X, y = _data()
    base = {"objective": "binary", "num_leaves": 31, "verbose": -1,
            "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=base)
    plain = lgb.train(dict(base), ds, num_boost_round=10)
    # huge coupled penalty on all but features 0/1
    pen = [0.0, 0.0] + [1e6] * (X.shape[1] - 2)
    p = dict(base, cegb_penalty_feature_coupled=pen)
    ds2 = lgb.Dataset(X, label=y, params=p)
    constrained = lgb.train(p, ds2, num_boost_round=10)
    assert _features_used(constrained) <= {0, 1}
    assert len(_features_used(plain)) > 2


def test_split_penalty_prunes_splits():
    X, y = _data(seed=1)
    base = {"objective": "binary", "num_leaves": 63, "verbose": -1,
            "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, params=base)
    plain = lgb.train(dict(base), ds, num_boost_round=5)
    p = dict(base, cegb_penalty_split=0.5)
    ds2 = lgb.Dataset(X, label=y, params=p)
    pruned = lgb.train(p, ds2, num_boost_round=5)
    n_plain = sum(t.num_leaves for t in plain._gbdt.models)
    n_pruned = sum(t.num_leaves for t in pruned._gbdt.models)
    assert n_pruned < n_plain


def test_tradeoff_split_scaling_equality():
    """(tradeoff=a, split=b) == (tradeoff=a*k, split=b/k): the delta is
    their product (reference: DetlaGain, hpp:50-52; equality tested in
    reference test_basic.py:262-300)."""
    X, y = _data(seed=2)
    base = {"objective": "binary", "num_leaves": 31, "verbose": -1,
            "min_data_in_leaf": 5}
    preds = []
    for tr, sp in ((1.0, 0.0004), (4.0, 0.0001)):
        p = dict(base, cegb_tradeoff=tr, cegb_penalty_split=sp)
        ds = lgb.Dataset(X, label=y, params=p)
        bst = lgb.train(p, ds, num_boost_round=8)
        preds.append(bst.predict(X))
    np.testing.assert_allclose(preds[0], preds[1], atol=1e-12)


def test_lazy_penalty_serial_path():
    """Lazy penalties prefer re-using features already paid for on the
    same rows; smoke: training works and reuses a narrower feature set."""
    X, y = _data(seed=3)
    p = {"objective": "binary", "num_leaves": 31, "verbose": -1,
         "min_data_in_leaf": 5,
         "cegb_penalty_feature_lazy": [1e6] * 6 + [0.0, 0.0]}
    ds = lgb.Dataset(X, label=y, params=p)
    bst = lgb.train(p, ds, num_boost_round=8)
    assert _features_used(bst) <= {6, 7}


def test_bad_penalty_length_raises():
    X, y = _data(seed=4)
    p = {"objective": "binary", "verbose": -1,
         "cegb_penalty_feature_coupled": [1.0, 2.0]}
    ds = lgb.Dataset(X, label=y, params=p)
    with pytest.raises(Exception):
        lgb.train(p, ds, num_boost_round=2)


def test_reference_cli_cegb_parity():
    """Reference-CLI oracle (tests/fixtures/ref_cegb_model.txt:
    binary example, num_trees=5, num_leaves=31, min_data_in_leaf=20,
    lr=0.1, cegb_penalty_split=0.02): the per-tree leaf counts under the
    split penalty must match the reference exactly, and the split
    structure of the first tree must agree."""
    import os
    fix = os.path.join(os.path.dirname(__file__), "fixtures")
    ref_txt = open(os.path.join(fix, "ref_cegb_model.txt")).read()

    raw = np.loadtxt(
        "/root/reference/examples/binary_classification/binary.train")
    y, X = raw[:, 0], raw[:, 1:]
    p = {"objective": "binary", "num_leaves": 31, "learning_rate": 0.1,
         "min_data_in_leaf": 20, "verbose": -1,
         "cegb_penalty_split": 0.02, "cegb_tradeoff": 1.0}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 5)
    ours = bst.model_to_string()

    def grab(txt, key):
        return [ln.split("=", 1)[1] for ln in txt.splitlines()
                if ln.startswith(key + "=")]

    ref_nl = grab(ref_txt, "num_leaves")  # one line per tree, no header
    our_nl = grab(ours, "num_leaves")
    assert our_nl == ref_nl, (our_nl, ref_nl)
    assert grab(ours, "split_feature")[0] == grab(ref_txt,
                                                  "split_feature")[0]
