"""Observability tooling: telemetry_report multi-process merging,
bench_history trajectory/regression flagging, the prof_kernels harness's
CPU smoke, and the end-to-end profile-mode CI smoke (train tiny with
telemetry+profile, then run the tools over the artifacts and
schema-validate the event stream)."""
import json
import os
import runpy
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.obs.report import (load_events, phase_skew, render,
                                     summarize, validate_events)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _write_events(path, events):
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")


def _iter_event(proc, i, phase_s):
    return {"event": "iteration", "t": 1.0 + i, "iteration": i,
            "num_class": 1, "leaves": [7], "waves": None,
            "iter_s": sum(phase_s.values()), "phase_s": phase_s,
            "metrics": {"training.auc": 0.9 + 0.001 * i + 0.0001 * proc},
            "counters": {}, "recompiles": 0,
            "cum_row_iters_per_s": 1000.0 * (i + 1)}


def _summary_event(phase_s, counters):
    return {"event": "summary", "t": 99.0, "phase_s": phase_s,
            "phase_calls": {k: 3 for k in phase_s}, "counters": counters}


# ---------------------------------------------------------------------------
# telemetry_report: multi-process merge
# ---------------------------------------------------------------------------

def test_report_merges_multiprocess_files(tmp_path):
    """Per-process telemetry.{i}.jsonl files merge into one digest:
    iteration rows from process 0, counters summed across processes,
    and the cross-host phase-skew table computed from the per-process
    summaries."""
    p0 = {"tree growth": 2.0, "boosting (grad/hess)": 0.5}
    p1 = {"tree growth": 3.0, "boosting (grad/hess)": 0.5}
    _write_events(tmp_path / "telemetry.0.jsonl",
                  [_iter_event(0, i, p0) for i in range(3)]
                  + [_summary_event(p0, {"collective/psum/traced_bytes":
                                         1000})])
    _write_events(tmp_path / "telemetry.1.jsonl",
                  [_iter_event(1, i, p1) for i in range(3)]
                  + [_summary_event(p1, {"collective/psum/traced_bytes":
                                         1200})])
    digest = summarize(load_events(str(tmp_path)))
    assert digest["processes"] == [0, 1]
    assert digest["iterations"] == 3
    # process-0 metrics picked for the per-iteration rows
    assert digest["per_iteration"][0]["metrics"]["training.auc"] == 0.9
    # counters summed across both processes' summaries
    assert digest["counters"]["collective/psum/traced_bytes"] == 2200
    # phase totals come from the summaries (both procs)
    assert digest["phase_s"]["tree growth"] == 5.0
    # the straggler table: proc 1 is 1s slower in tree growth
    skew = digest["phase_skew"]["tree growth"]
    assert skew["min_s"] == 2.0 and skew["max_s"] == 3.0
    assert skew["spread_s"] == 1.0
    assert skew["spread_frac"] == pytest.approx(1.0 / 2.5)
    # identical phases show no skew
    assert digest["phase_skew"]["boosting (grad/hess)"]["spread_s"] == 0.0
    text = render(digest)
    assert "phase skew" in text and "tree growth" in text


def test_phase_skew_single_process_empty():
    assert phase_skew({0: {"a": 1.0}}) == {}


def test_report_tool_cli_multiprocess(tmp_path, capsys, monkeypatch):
    p0 = {"tree growth": 1.0}
    _write_events(tmp_path / "telemetry.0.jsonl",
                  [_iter_event(0, 0, p0), _summary_event(p0, {})])
    _write_events(tmp_path / "telemetry.1.jsonl",
                  [_iter_event(1, 0, p0), _summary_event(p0, {})])
    tool = os.path.join(TOOLS, "telemetry_report.py")
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path), "--json"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0
    digest = json.loads(capsys.readouterr().out)
    assert digest["processes"] == [0, 1]


# ---------------------------------------------------------------------------
# bench_history: trajectory + regression flagging
# ---------------------------------------------------------------------------

def _bench_round(n, value, per_iter_s, backend=None, **extra):
    parsed = {"metric": "train_throughput", "value": value,
              "unit": "row_iters/s", "vs_baseline": value / 2.2e7,
              "rows": 1000, "iters": 5, "num_leaves": 31, "max_bin": 255,
              "per_iter_s": per_iter_s, "compile_s": 3.0,
              "train_auc": 0.9}
    if backend:
        parsed["backend"] = backend
    parsed.update(extra)
    return {"n": n, "cmd": "python bench.py", "rc": 0, "parsed": parsed}


def _history(tmp_path, rounds, *args):
    sys.path.insert(0, TOOLS)
    try:
        import bench_history
    finally:
        sys.path.remove(TOOLS)
    for i, r in enumerate(rounds, 1):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as fh:
            json.dump(r, fh)
    rows = bench_history.collect([str(tmp_path)])
    return bench_history, rows


def test_bench_history_flags_regression(tmp_path):
    bh, rows = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0),
        _bench_round(2, 2000.0, 0.5),
        _bench_round(3, 1200.0, 0.9,           # 40% throughput drop vs r02
                     peak_hbm_bytes=5_000_000),
    ])
    assert [r["round"] for r in rows] == ["r01", "r02", "r03"]
    regs = bh.find_regressions(rows, threshold=0.1)
    by_metric = {r["metric"]: r for r in regs}
    assert "value" in by_metric
    assert by_metric["value"]["best_round"] == "r02"
    assert by_metric["value"]["change_frac"] == pytest.approx(-0.4)
    assert "per_iter_s" in by_metric      # lower-is-better direction
    assert by_metric["per_iter_s"]["change_frac"] == pytest.approx(0.8)
    # peak_hbm_bytes only exists in r03 — no prior, no flag
    assert "peak_hbm_bytes" not in by_metric
    text = bh.render(rows, regs)
    assert "REGRESSIONS" in text and "value" in text


def test_bench_history_no_flags_when_improving(tmp_path):
    bh, rows = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0),
        _bench_round(2, 3000.0, 0.3),
    ])
    assert bh.find_regressions(rows, threshold=0.1) == []


def test_bench_history_contexts_not_comparable(tmp_path):
    """A CPU-fallback round must not 'regress' against a real round."""
    bh, rows = _history(tmp_path, [
        _bench_round(1, 100000.0, 0.1),
        _bench_round(2, 500.0, 2.0, backend="cpu-fallback"),
    ])
    assert bh.find_regressions(rows, threshold=0.1) == []


def test_bench_history_unparsed_round_and_telemetry_fold(tmp_path):
    """parsed:null rounds ride along noteless-metric; embedded telemetry
    digests contribute peak-HBM and kernel roofline trajectory metrics."""
    td = {"phase_s": {"tree growth": 1.0}, "phase_calls": {},
          "counters": {"jax/compiles": 7},
          "kernels": {"lgbm/grow_apply": {"calls": 3, "achieved_s": 1.0,
                                          "roofline_s": 0.2,
                                          "roofline_frac": 0.2}},
          "memory": {"peak_bytes": 123456, "peak_phase": "tree growth"}}
    bh, rows = _history(tmp_path, [
        {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": None},
        _bench_round(2, 1000.0, 1.0, telemetry=td),
    ])
    assert rows[0]["note"] == "no parsed bench line"
    m = rows[1]["metrics"]
    assert m["peak_hbm_bytes"] == 123456
    assert m["kernel_roofline/lgbm/grow_apply"] == 0.2
    assert m["jax_compiles"] == 7


def test_bench_history_cli_exit_codes(tmp_path, monkeypatch, capsys):
    tool = os.path.join(TOOLS, "bench_history.py")
    for i, r in enumerate([_bench_round(1, 2000.0, 0.5),
                           _bench_round(2, 1000.0, 1.0)], 1):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as fh:
            json.dump(r, fh)
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path), "--json"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0          # flags reported, exit 0 by default
    out = json.loads(capsys.readouterr().out)
    assert any(g["metric"] == "value" for g in out["regressions"])
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path),
                                      "--fail-on-regression"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 1


# ---------------------------------------------------------------------------
# prof_kernels: CPU interpret smoke
# ---------------------------------------------------------------------------

def test_prof_kernels_interpret_smoke(tmp_path, monkeypatch, capsys):
    """The promoted harness runs its kernel leg on CPU via PROF_INTERPRET
    and reports measured + roofline + fraction with nonzero cost-model
    numbers (the between-TPU-windows guard the old prof_decompose.py
    never had)."""
    for k, v in {"PROF_INTERPRET": "1", "PROF_ROWS": "1536",
                 "PROF_FEATURES": "4", "PROF_LEAVES": "7",
                 "PROF_CAPACITY": "4", "PROF_REPEAT": "1",
                 "PROF_LEGS": "kernel", "PROF_JSON": "1"}.items():
        monkeypatch.setenv(k, v)
    tool = os.path.join(TOOLS, "prof_kernels.py")
    monkeypatch.setattr(sys, "argv", [tool])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])
    leg = payload["legs"]["kernel full pass"]
    assert leg["seconds"] > 0
    assert leg["flops"] > 0 and leg["bytes"] > 0
    assert leg["roofline_s"] > 0 and leg["roofline_frac"] > 0


def test_wave_kernel_cost_matches_roofline_doc():
    """wave_kernel_cost at the HIGGS bench shape reproduces the 3.67
    TFLOP / ~9.3 ms numbers docs/ROOFLINE.md quotes for v5e."""
    from lightgbm_tpu.obs.profile import roofline_seconds
    from lightgbm_tpu.ops.pallas_hist import wave_kernel_cost
    flops, nbytes = wave_kernel_cost(1_000_000, 28, 256, "2xbf16")
    assert flops == pytest.approx(2 * 2 * 256 * 128 * 1e6 * 28)
    t = roofline_seconds(flops, nbytes, peaks=(394e12, 820e9))
    assert t == pytest.approx(9.3e-3, rel=0.02)
    # feature packing: B=64 really is 4x cheaper
    flops64, _ = wave_kernel_cost(1_000_000, 28, 64, "2xbf16")
    assert flops64 == pytest.approx(flops / 4)


# ---------------------------------------------------------------------------
# end-to-end CI smoke: profile-mode train -> tools over the artifacts
# ---------------------------------------------------------------------------

def test_profile_smoke_end_to_end(tmp_path):
    """Tier-1-safe acceptance smoke: train a tiny model with telemetry +
    profile enabled in a fresh CPU interpreter, then run
    telemetry_report.py and bench_history.py over the artifacts and
    schema-validate the kernel_profile / memory_census events."""
    sink = tmp_path / "telem"
    code = (
        "import json, numpy as np, lightgbm_tpu as lgb\n"
        "from lightgbm_tpu import obs\n"
        "rng = np.random.default_rng(0)\n"
        "X = rng.normal(size=(400, 5)); y = (X[:, 0] > 0).astype(float)\n"
        "p = {'objective': 'binary', 'num_leaves': 5, 'tpu_profile': True,\n"
        "     'min_data_in_leaf': 5, 'verbose': -1}\n"
        "bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 3)\n"
        "assert bst.num_trees() == 3\n"
        "assert obs.profile_enabled() and obs.peak_bytes() > 0\n")
    env = dict(os.environ)
    env["LGBM_TPU_TELEMETRY"] = str(sink)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr

    events = load_events(str(sink))
    assert validate_events(events) == [], validate_events(events)
    kp = [e for e in events if e.get("event") == "kernel_profile"]
    assert kp and all(e["flops"] > 0 and e["bytes"] > 0
                      and e["roofline_frac"] > 0 for e in kp)
    mc = [e for e in events if e.get("event") == "memory_census"]
    assert mc and mc[-1]["peak_bytes"] > 0

    # telemetry_report over the artifact
    rep = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "telemetry_report.py"),
         str(sink), "--json"], capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    digest = json.loads(rep.stdout)
    assert digest["iterations"] == 3
    assert digest["kernels"] and digest["memory"]["peak_bytes"] > 0

    # bench_history over a bench-shaped round embedding that digest
    row = {"n": 1, "rc": 0,
           "parsed": {"value": 1000.0, "rows": 400, "iters": 3,
                      "num_leaves": 5, "max_bin": 255,
                      "peak_hbm_bytes": digest["memory"]["peak_bytes"],
                      "telemetry": {"kernels": digest["kernels"],
                                    "memory": digest["memory"],
                                    "counters": digest["counters"]}}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(row, fh)
    bh = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "bench_history.py"),
         str(tmp_path), "--json"], capture_output=True, text=True,
        timeout=60)
    assert bh.returncode == 0, bh.stderr
    hist = json.loads(bh.stdout)
    assert hist["rounds"][0]["metrics"]["peak_hbm_bytes"] > 0
    assert hist["regressions"] == []
