"""Observability tooling: telemetry_report multi-process merging,
bench_history trajectory/regression flagging, the prof_kernels harness's
CPU smoke, and the end-to-end profile-mode CI smoke (train tiny with
telemetry+profile, then run the tools over the artifacts and
schema-validate the event stream)."""
import json
import os
import runpy
import subprocess
import sys

import numpy as np
import pytest

from lightgbm_tpu.obs.report import (load_events, phase_skew, render,
                                     summarize, validate_events)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _write_events(path, events):
    with open(path, "w") as fh:
        for e in events:
            fh.write(json.dumps(e) + "\n")


def _iter_event(proc, i, phase_s):
    return {"event": "iteration", "t": 1.0 + i, "iteration": i,
            "num_class": 1, "leaves": [7], "waves": None,
            "iter_s": sum(phase_s.values()), "phase_s": phase_s,
            "metrics": {"training.auc": 0.9 + 0.001 * i + 0.0001 * proc},
            "counters": {}, "recompiles": 0,
            "cum_row_iters_per_s": 1000.0 * (i + 1)}


def _summary_event(phase_s, counters):
    return {"event": "summary", "t": 99.0, "phase_s": phase_s,
            "phase_calls": {k: 3 for k in phase_s}, "counters": counters}


# ---------------------------------------------------------------------------
# telemetry_report: multi-process merge
# ---------------------------------------------------------------------------

def test_report_merges_multiprocess_files(tmp_path):
    """Per-process telemetry.{i}.jsonl files merge into one digest:
    iteration rows from process 0, counters summed across processes,
    and the cross-host phase-skew table computed from the per-process
    summaries."""
    p0 = {"tree growth": 2.0, "boosting (grad/hess)": 0.5}
    p1 = {"tree growth": 3.0, "boosting (grad/hess)": 0.5}
    _write_events(tmp_path / "telemetry.0.jsonl",
                  [_iter_event(0, i, p0) for i in range(3)]
                  + [_summary_event(p0, {"collective/psum/traced_bytes":
                                         1000})])
    _write_events(tmp_path / "telemetry.1.jsonl",
                  [_iter_event(1, i, p1) for i in range(3)]
                  + [_summary_event(p1, {"collective/psum/traced_bytes":
                                         1200})])
    digest = summarize(load_events(str(tmp_path)))
    assert digest["processes"] == [0, 1]
    assert digest["iterations"] == 3
    # process-0 metrics picked for the per-iteration rows
    assert digest["per_iteration"][0]["metrics"]["training.auc"] == 0.9
    # counters summed across both processes' summaries
    assert digest["counters"]["collective/psum/traced_bytes"] == 2200
    # phase totals come from the summaries (both procs)
    assert digest["phase_s"]["tree growth"] == 5.0
    # the straggler table: proc 1 is 1s slower in tree growth
    skew = digest["phase_skew"]["tree growth"]
    assert skew["min_s"] == 2.0 and skew["max_s"] == 3.0
    assert skew["spread_s"] == 1.0
    assert skew["spread_frac"] == pytest.approx(1.0 / 2.5)
    # identical phases show no skew
    assert digest["phase_skew"]["boosting (grad/hess)"]["spread_s"] == 0.0
    text = render(digest)
    assert "phase skew" in text and "tree growth" in text


def test_phase_skew_single_process_empty():
    assert phase_skew({0: {"a": 1.0}}) == {}


def test_report_tool_cli_multiprocess(tmp_path, capsys, monkeypatch):
    p0 = {"tree growth": 1.0}
    _write_events(tmp_path / "telemetry.0.jsonl",
                  [_iter_event(0, 0, p0), _summary_event(p0, {})])
    _write_events(tmp_path / "telemetry.1.jsonl",
                  [_iter_event(1, 0, p0), _summary_event(p0, {})])
    tool = os.path.join(TOOLS, "telemetry_report.py")
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path), "--json"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0
    digest = json.loads(capsys.readouterr().out)
    assert digest["processes"] == [0, 1]


# ---------------------------------------------------------------------------
# bench_history: trajectory + regression flagging
# ---------------------------------------------------------------------------

def _bench_round(n, value, per_iter_s, backend=None, **extra):
    parsed = {"metric": "train_throughput", "value": value,
              "unit": "row_iters/s", "vs_baseline": value / 2.2e7,
              "rows": 1000, "iters": 5, "num_leaves": 31, "max_bin": 255,
              "per_iter_s": per_iter_s, "compile_s": 3.0,
              "train_auc": 0.9}
    if backend:
        parsed["backend"] = backend
    parsed.update(extra)
    return {"n": n, "cmd": "python bench.py", "rc": 0, "parsed": parsed}


def _history(tmp_path, rounds, *args):
    sys.path.insert(0, TOOLS)
    try:
        import bench_history
    finally:
        sys.path.remove(TOOLS)
    for i, r in enumerate(rounds, 1):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as fh:
            json.dump(r, fh)
    rows = bench_history.collect([str(tmp_path)])
    return bench_history, rows


def test_bench_history_flags_regression(tmp_path):
    bh, rows = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0),
        _bench_round(2, 2000.0, 0.5),
        _bench_round(3, 1200.0, 0.9,           # 40% throughput drop vs r02
                     peak_hbm_bytes=5_000_000),
    ])
    assert [r["round"] for r in rows] == ["r01", "r02", "r03"]
    regs = bh.find_regressions(rows, threshold=0.1)
    by_metric = {r["metric"]: r for r in regs}
    assert "value" in by_metric
    assert by_metric["value"]["best_round"] == "r02"
    assert by_metric["value"]["change_frac"] == pytest.approx(-0.4)
    assert "per_iter_s" in by_metric      # lower-is-better direction
    assert by_metric["per_iter_s"]["change_frac"] == pytest.approx(0.8)
    # peak_hbm_bytes only exists in r03 — no prior, no flag
    assert "peak_hbm_bytes" not in by_metric
    text = bh.render(rows, regs)
    assert "REGRESSIONS" in text and "value" in text


def test_bench_history_no_flags_when_improving(tmp_path):
    bh, rows = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0),
        _bench_round(2, 3000.0, 0.3),
    ])
    assert bh.find_regressions(rows, threshold=0.1) == []


def test_bench_history_contexts_not_comparable(tmp_path):
    """A CPU-fallback round must not 'regress' against a real round."""
    bh, rows = _history(tmp_path, [
        _bench_round(1, 100000.0, 0.1),
        _bench_round(2, 500.0, 2.0, backend="cpu-fallback"),
    ])
    assert bh.find_regressions(rows, threshold=0.1) == []


def test_bench_history_unparsed_round_and_telemetry_fold(tmp_path):
    """parsed:null rounds ride along noteless-metric; embedded telemetry
    digests contribute peak-HBM and kernel roofline trajectory metrics."""
    td = {"phase_s": {"tree growth": 1.0}, "phase_calls": {},
          "counters": {"jax/compiles": 7},
          "kernels": {"lgbm/grow_apply": {"calls": 3, "achieved_s": 1.0,
                                          "roofline_s": 0.2,
                                          "roofline_frac": 0.2}},
          "memory": {"peak_bytes": 123456, "peak_phase": "tree growth"}}
    bh, rows = _history(tmp_path, [
        {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": None},
        _bench_round(2, 1000.0, 1.0, telemetry=td),
    ])
    assert rows[0]["note"] == "no parsed bench line"
    m = rows[1]["metrics"]
    assert m["peak_hbm_bytes"] == 123456
    assert m["kernel_roofline/lgbm/grow_apply"] == 0.2
    assert m["jax_compiles"] == 7


def test_bench_history_canary_trend(tmp_path):
    """Degraded-backend rounds stay out of regression baselines but their
    per_iter_s/value movement is surfaced as an informational trend — a
    partition-style win is visible even with no TPU datapoint."""
    bh, rows = _history(tmp_path, [
        _bench_round(1, 500.0, 2.0, backend="cpu-fallback"),
        _bench_round(2, 1000.0, 1.0, backend="cpu-fallback"),
    ])
    trend = bh.canary_trend(rows)
    assert [t["round"] for t in trend] == ["r01", "r02"]
    assert trend[1]["per_iter_s_change_frac"] == pytest.approx(-0.5)
    assert trend[1]["value_change_frac"] == pytest.approx(1.0)
    # canaries still gate NOTHING
    assert bh.find_regressions(rows, threshold=0.05) == []
    text = bh.render(rows, [])
    assert "canary trend" in text and "-50.0%" in text


def test_bench_history_mode_regressions(tmp_path):
    """Wave-pipeline stamps: waves_per_tree trends numerically (lower is
    better) while hist_mode / fused_sibling downgrades are flagged
    categorically — even when throughput improved, because a bf16 round
    can post a better value while computing a worse histogram."""
    bh, rows = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0, waves_per_tree=16.0,
                     hist_mode="2xbf16", fused_sibling=True),
        _bench_round(2, 1500.0, 0.7, waves_per_tree=19.0,
                     hist_mode="f32", fused_sibling=False),
    ])
    assert rows[0]["mode"] == {"hist_mode": "2xbf16",
                               "fused_sibling": True}
    regs = bh.find_regressions(rows, threshold=0.1)
    by_metric = {r["metric"]: r for r in regs}
    assert "waves_per_tree" in by_metric       # lower-is-better numeric
    mregs = bh.find_mode_regressions(rows)
    assert {m["metric"] for m in mregs} == {"fused_sibling", "hist_mode"}
    text = bh.render(rows, regs, mregs)
    assert "MODE REGRESSIONS" in text and "2xbf16" in text
    # same modes, no prior downgrade → nothing flagged
    bh2, rows2 = _history(tmp_path, [
        _bench_round(1, 1000.0, 1.0, hist_mode="2xbf16",
                     fused_sibling=True),
        _bench_round(2, 900.0, 1.1, hist_mode="2xbf16",
                     fused_sibling=True),
    ])
    assert bh2.find_mode_regressions(rows2) == []


def _serve_round(n, blip=None, steady=None, rollbacks=0):
    return {"n": n, "parsed": {
        "kind": "serve", "backend": "cpu", "trees": 20, "max_batch": 256,
        "closed": {"rows_per_s": 5000.0, "p50_ms": 5.0, "p99_ms": 20.0},
        "open": {"p99_ms": 25.0},
        "server": {"p99_ms": 18.0, "slo_burn": 0.1},
        "occupancy": 0.9, "compiles": 10,
        "swap": {"swap_blip_p99_ms": blip, "steady_p99_ms": steady,
                 "rollbacks": rollbacks}}}


def test_bench_history_swap_blip_flag(tmp_path):
    """A hot-swap blip p99 worse than 2x the steady p99 (and any
    rollback during the swap leg) is flagged on the serving round —
    categorical, like mode regressions, because a blip can double while
    the steady p99 improves."""
    sys.path.insert(0, TOOLS)
    try:
        import bench_history as bh
    finally:
        sys.path.remove(TOOLS)
    with open(tmp_path / "SERVE_r01.json", "w") as fh:
        json.dump(_serve_round(1, blip=90.0, steady=20.0, rollbacks=1),
                  fh)
    with open(tmp_path / "SERVE_r02.json", "w") as fh:
        json.dump(_serve_round(2, blip=30.0, steady=20.0), fh)
    rows = bh.collect([str(tmp_path)])
    assert rows[0]["metrics"]["serve_swap_blip_p99_ms"] == 90.0
    assert rows[0]["swap_blip"] == 4.5
    assert "rollback" in rows[0]["note"]
    assert "swap_blip" not in rows[1]          # 1.5x steady: no flag
    blips = bh.find_swap_blips(rows)
    assert [b["round"] for b in blips] == ["r01"]
    text = bh.render(rows, [], [], blips)
    assert "SWAP BLIPS" in text and "4.5x" in text


def test_run_suite_chaos_tier_stubbed():
    """The chaos tier wraps chaos_serve.py --json; its check map becomes
    the tier's counts and it rides the default tier list."""
    rs = _import_tool("run_suite")
    assert "chaos" in rs._TOOL_TIERS

    def fake(argv, **kw):
        import types
        assert any(isinstance(a, str) and "chaos_serve.py" in a
                   for a in argv)
        line = json.dumps({"kind": "chaos_serve", "ok": True,
                           "checks": {"wedge.all_served": True,
                                      "swap.zero_loss": True,
                                      "rollback.triggered": True}})
        return types.SimpleNamespace(returncode=0, stdout=line + "\n",
                                     stderr="")

    res = rs.run_tool_smoke("chaos", 60, runner=fake)
    assert res["ok"] is True
    assert res["counts"] == {"passed": 3, "failed": 0}


def test_bench_history_cli_exit_codes(tmp_path, monkeypatch, capsys):
    tool = os.path.join(TOOLS, "bench_history.py")
    for i, r in enumerate([_bench_round(1, 2000.0, 0.5),
                           _bench_round(2, 1000.0, 1.0)], 1):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as fh:
            json.dump(r, fh)
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path), "--json"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0          # flags reported, exit 0 by default
    out = json.loads(capsys.readouterr().out)
    assert any(g["metric"] == "value" for g in out["regressions"])
    monkeypatch.setattr(sys, "argv", [tool, str(tmp_path),
                                      "--fail-on-regression"])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 1


# ---------------------------------------------------------------------------
# prof_kernels: CPU interpret smoke
# ---------------------------------------------------------------------------

def test_prof_kernels_interpret_smoke(tmp_path, monkeypatch, capsys):
    """The promoted harness runs its kernel leg on CPU via PROF_INTERPRET
    and reports measured + roofline + fraction with nonzero cost-model
    numbers (the between-TPU-windows guard the old prof_decompose.py
    never had)."""
    for k, v in {"PROF_INTERPRET": "1", "PROF_ROWS": "1536",
                 "PROF_FEATURES": "4", "PROF_LEAVES": "7",
                 "PROF_CAPACITY": "4", "PROF_REPEAT": "1",
                 "PROF_LEGS": "kernel", "PROF_JSON": "1"}.items():
        monkeypatch.setenv(k, v)
    tool = os.path.join(TOOLS, "prof_kernels.py")
    monkeypatch.setattr(sys, "argv", [tool])
    with pytest.raises(SystemExit) as ei:
        runpy.run_path(tool, run_name="__main__")
    assert ei.value.code == 0
    out = capsys.readouterr().out.strip().splitlines()
    payload = json.loads(out[-1])
    leg = payload["legs"]["kernel full pass"]
    assert leg["seconds"] > 0
    assert leg["flops"] > 0 and leg["bytes"] > 0
    assert leg["roofline_s"] > 0 and leg["roofline_frac"] > 0


def test_wave_kernel_cost_matches_roofline_doc():
    """wave_kernel_cost at the HIGGS bench shape reproduces the 3.67
    TFLOP / ~9.3 ms numbers docs/ROOFLINE.md quotes for v5e."""
    from lightgbm_tpu.obs.profile import roofline_seconds
    from lightgbm_tpu.ops.pallas_hist import wave_kernel_cost
    flops, nbytes = wave_kernel_cost(1_000_000, 28, 256, "2xbf16")
    assert flops == pytest.approx(2 * 2 * 256 * 128 * 1e6 * 28)
    t = roofline_seconds(flops, nbytes, peaks=(394e12, 820e9))
    assert t == pytest.approx(9.3e-3, rel=0.02)
    # feature packing: B=64 really is 4x cheaper
    flops64, _ = wave_kernel_cost(1_000_000, 28, 64, "2xbf16")
    assert flops64 == pytest.approx(flops / 4)


# ---------------------------------------------------------------------------
# end-to-end CI smoke: profile-mode train -> tools over the artifacts
# ---------------------------------------------------------------------------

def test_profile_smoke_end_to_end(tmp_path):
    """Tier-1-safe acceptance smoke: train a tiny model with telemetry +
    profile enabled in a fresh CPU interpreter, then run
    telemetry_report.py and bench_history.py over the artifacts and
    schema-validate the kernel_profile / memory_census events."""
    sink = tmp_path / "telem"
    code = (
        "import json, numpy as np, lightgbm_tpu as lgb\n"
        "from lightgbm_tpu import obs\n"
        "rng = np.random.default_rng(0)\n"
        "X = rng.normal(size=(400, 5)); y = (X[:, 0] > 0).astype(float)\n"
        "p = {'objective': 'binary', 'num_leaves': 5, 'tpu_profile': True,\n"
        "     'min_data_in_leaf': 5, 'verbose': -1}\n"
        "bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 3)\n"
        "assert bst.num_trees() == 3\n"
        "assert obs.profile_enabled() and obs.peak_bytes() > 0\n")
    env = dict(os.environ)
    env["LGBM_TPU_TELEMETRY"] = str(sink)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, env=env, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr

    events = load_events(str(sink))
    assert validate_events(events) == [], validate_events(events)
    kp = [e for e in events if e.get("event") == "kernel_profile"]
    assert kp and all(e["flops"] > 0 and e["bytes"] > 0
                      and e["roofline_frac"] > 0 for e in kp)
    mc = [e for e in events if e.get("event") == "memory_census"]
    assert mc and mc[-1]["peak_bytes"] > 0

    # telemetry_report over the artifact
    rep = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "telemetry_report.py"),
         str(sink), "--json"], capture_output=True, text=True, timeout=60)
    assert rep.returncode == 0, rep.stderr
    digest = json.loads(rep.stdout)
    assert digest["iterations"] == 3
    assert digest["kernels"] and digest["memory"]["peak_bytes"] > 0

    # bench_history over a bench-shaped round embedding that digest
    row = {"n": 1, "rc": 0,
           "parsed": {"value": 1000.0, "rows": 400, "iters": 3,
                      "num_leaves": 5, "max_bin": 255,
                      "peak_hbm_bytes": digest["memory"]["peak_bytes"],
                      "telemetry": {"kernels": digest["kernels"],
                                    "memory": digest["memory"],
                                    "counters": digest["counters"]}}}
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(row, fh)
    bh = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "bench_history.py"),
         str(tmp_path), "--json"], capture_output=True, text=True,
        timeout=60)
    assert bh.returncode == 0, bh.stderr
    hist = json.loads(bh.stdout)
    assert hist["rounds"][0]["metrics"]["peak_hbm_bytes"] > 0
    assert hist["regressions"] == []


# ---------------------------------------------------------------------------
# bench_history: degraded-backend canaries (VERDICT round-5 weak #4)
# ---------------------------------------------------------------------------

def test_bench_history_canary_rounds_excluded_from_baselines(tmp_path):
    """cpu-fallback rounds are flagged in the table and excluded from the
    regression comparison on BOTH sides — even against each other."""
    bh, rows = _history(tmp_path, [
        _bench_round(1, 100000.0, 0.1),
        _bench_round(2, 5000.0, 1.0, backend="cpu-fallback"),
        _bench_round(3, 500.0, 2.0, backend="cpu-fallback"),  # 90% "drop"
    ])
    assert rows[1]["canary"] == "cpu-fallback"
    assert rows[2]["canary"] == "cpu-fallback"
    assert "canary" not in rows[0]
    # two comparable canaries with a huge drop: still no regression,
    # because canaries never enter the baseline
    assert bh.find_regressions(rows, threshold=0.1) == []
    text = bh.render(rows, [])
    assert "canary — excluded from baselines" in text
    # and a canary is never the "latest" round a real regression is
    # computed for: a real r04 regressing vs r01 still flags
    bh2, rows2 = _history(tmp_path, [
        _bench_round(1, 100000.0, 0.1),
        _bench_round(2, 500.0, 2.0, backend="cpu-fallback"),
        _bench_round(3, 50000.0, 0.2),
        _bench_round(4, 500.0, 2.0, backend="cpu-forced"),
    ])
    regs = bh2.find_regressions(rows2, threshold=0.1)
    by_metric = {r["metric"]: r for r in regs}
    assert by_metric["value"]["round"] == "r03"
    assert by_metric["value"]["best_round"] == "r01"


# ---------------------------------------------------------------------------
# run_suite: per-tier evidence artifact (SUITE_rN.json)
# ---------------------------------------------------------------------------

def _import_tool(name):
    sys.path.insert(0, TOOLS)
    try:
        return __import__(name)
    finally:
        sys.path.remove(TOOLS)


def test_run_suite_parse_counts():
    rs = _import_tool("run_suite")
    out = ("....s..\n"
           "= 5 passed, 1 skipped, 2 deselected, 1 warning in 12.34s =\n")
    c = rs.parse_counts(out)
    assert c == {"passed": 5, "skipped": 1, "deselected": 2, "warning": 1}
    assert rs.parse_counts("3 failed, 2 passed, 1 error in 9s") == {
        "failed": 3, "passed": 2, "error": 1}
    assert rs.parse_counts("garbage") == {}


def test_run_suite_smoke_tiny_selection(tmp_path):
    """The satellite smoke: run_suite against a single tiny quick test
    writes a SUITE_rN.json with per-tier wall clock and pass counts."""
    rs = _import_tool("run_suite")
    rc = rs.main([
        "--tiers", "quick",
        "--select",
        "tests/test_distributed.py::test_parse_machine_list_forms",
        "--out", str(tmp_path), "--timeout", "300"])
    assert rc == 0
    path = tmp_path / "SUITE_r01.json"
    assert path.exists()
    rec = json.loads(path.read_text())
    assert rec["ok"] is True
    assert rec["failed"] == 0
    tier = rec["tiers"]["quick"]
    assert tier["counts"].get("passed") == 1
    assert tier["wall_s"] > 0
    # round numbering advances
    assert rs.next_round(str(tmp_path)) == 2


def test_run_suite_reports_failure(tmp_path):
    """A failing selection yields ok=False and exit 1 (the 0-failure
    evidence must be falsifiable)."""
    rs = _import_tool("run_suite")
    bad = tmp_path / "test_sentinel_fail.py"
    bad.write_text("import pytest\n"
                   "@pytest.mark.quick\n"
                   "def test_always_fails():\n    assert False\n")
    rc = rs.main(["--tiers", "quick", "--select", str(bad),
                  "--out", str(tmp_path), "--timeout", "300"])
    assert rc == 1
    rec = json.loads((tmp_path / "SUITE_r01.json").read_text())
    assert rec["ok"] is False
    assert rec["failed"] == 1


def test_run_suite_serve_leg_stubbed():
    """The serve tier wraps bench_serve.py --smoke: its check map becomes
    the tier's pass/fail counts and a failing check fails the tier."""
    rs = _import_tool("run_suite")

    def fake_ok(argv, **kw):
        import types
        line = json.dumps({"kind": "serve", "ok": True,
                           "checks": {"p99_recorded": True,
                                      "compiles_bounded": True,
                                      "clean_shutdown": True}})
        return types.SimpleNamespace(returncode=0, stdout=line + "\n",
                                     stderr="")

    res = rs.run_serve_smoke(60, runner=fake_ok)
    assert res["ok"] is True
    assert res["counts"] == {"passed": 3, "failed": 0}

    def fake_bad(argv, **kw):
        import types
        line = json.dumps({"kind": "serve", "ok": False,
                           "checks": {"p99_recorded": True,
                                      "compiles_bounded": False}})
        return types.SimpleNamespace(returncode=1, stdout=line + "\n",
                                     stderr="")

    res = rs.run_serve_smoke(60, runner=fake_bad)
    assert res["ok"] is False
    assert res["counts"]["failed"] == 1


# ---------------------------------------------------------------------------
# tpu_window: self-arming measurement watcher
# ---------------------------------------------------------------------------

class _FakeRun:
    """Canned subprocess.run: records invocations, returns scripted
    (returncode, stdout) keyed on a substring of the argv."""

    def __init__(self, outputs, default=(0, "")):
        self.outputs = outputs
        self.default = default
        self.calls = []

    def __call__(self, argv, **kw):
        self.calls.append(argv)
        import types
        r = types.SimpleNamespace()
        key = next((k for k in self.outputs
                    if any(isinstance(a, str) and k in a for a in argv)),
                   None)
        r.returncode, r.stdout = (self.outputs[key] if key is not None
                                  else self.default)
        r.stderr = ""
        return r


def test_tpu_window_probe_and_rounds(tmp_path):
    tw = _import_tool("tpu_window")
    armed, backend = tw.probe_backend(
        runner=_FakeRun({}, default=(0, "TPU v5 lite\n")))
    assert armed and backend == "TPU v5 lite"
    armed, backend = tw.probe_backend(
        runner=_FakeRun({}, default=(2, "cpu\n")))
    assert not armed and backend == "cpu"
    assert tw.next_round(str(tmp_path)) == 1
    (tmp_path / "BENCH_manual_r03.json").write_text("{}")
    assert tw.next_round(str(tmp_path)) == 4
    assert tw._parse_json_tail("junk\n{\"a\": 1}\ntrailer") == {"a": 1}
    assert tw._parse_json_tail("no json") is None


def test_tpu_window_checklist_stubbed(tmp_path):
    """The full checklist plumbing with canned leg outputs: artifact
    layout, the bench_history-compatible BENCH_manual record, and the
    health summary — no real training."""
    tw = _import_tool("tpu_window")
    bench_line = json.dumps({"metric": "train_throughput", "value": 123.0,
                             "unit": "row_iters/s", "vs_baseline": 0.001,
                             "rows": 100, "iters": 3, "num_leaves": 31,
                             "max_bin": 255, "backend": "cpu-forced",
                             "health_checks": 9, "health_failures": 0})
    serve_line = json.dumps({"kind": "serve", "backend": "cpu",
                             "trees": 20, "max_batch": 128,
                             "closed": {"rows_per_s": 9000.0,
                                        "p99_ms": 12.0},
                             "open": {"p99_ms": 15.0,
                                      "explain_frac": 0.5,
                                      "explain_p99_ms": 48.0},
                             "occupancy": 0.7, "compiles": 8,
                             "degraded": False})
    ingest_line = json.dumps({"kind": "ingest", "backend": "cpu",
                              "rows": 60000, "features": 8,
                              "chunk_rows": 2048, "memmap": False,
                              "ingest_rows_per_s": 250000.0,
                              "ingest_wall_s": 0.24,
                              "checks": {"bounded_memory": True},
                              "ok": True})
    fleet_line = json.dumps({"kind": "fleet", "fleet_ranks": 3,
                             "fleet_recoveries": 1, "wall_s": 60.0,
                             "checks": {"fleet.plain.bit_exact": True},
                             "ok": True})
    fake = _FakeRun({
        "bench_serve.py": (0, serve_line + "\n"),
        "ingest_bench.py": (0, ingest_line + "\n"),
        "fleet_smoke.py": (0, fleet_line + "\n"),
        "bench.py": (0, "noise\n" + bench_line + "\n"),
        "prof_kernels.py": (0, json.dumps({"tool": "prof_kernels",
                                           "legs": {}}) + "\n"),
        "-c": (0, "TRACE_OK\n"),
    })
    rec = tw.run_checklist(str(tmp_path), 7, dry_run=True, runner=fake,
                           backend="cpu (dry-run)")
    assert (tmp_path / "BENCH_manual_r07.json").exists()
    assert (tmp_path / "HEALTH_manual_r07.json").exists()
    assert rec["parsed"]["value"] == 123.0
    assert rec["parsed"]["health_failures"] == 0
    assert set(rec["legs"]) == {"bench", "bench_profile",
                                "bench_maxbin63", "bench_unfused",
                                "bench_quant", "bench_nofusedgrad",
                                "bench_rank", "prof_kernels",
                                "bench_serve", "bench_explain",
                                "bench_ingest", "bench_fleet", "trace"}
    assert (tmp_path / "FLEET_manual_r07.json").exists()
    assert all(leg["rc"] == 0 for leg in rec["legs"].values())
    # bench legs ran seven times (clean, profile, maxbin63, unfused,
    # quant, nofusedgrad, rank) — endswith, so tools/ingest_bench.py's
    # leg is not miscounted as a bench.py invocation
    bench_calls = [c for c in fake.calls
                   if any(isinstance(a, str)
                          and a.endswith(os.sep + "bench.py")
                          for a in c)]
    assert len(bench_calls) == 7
    # the rank leg's parsed line landed as BENCH_rank_manual_rN.json
    # and bench_history's BENCH_r* glob picks it up as its own context
    assert (tmp_path / "BENCH_rank_manual_r07.json").exists()
    # the record is bench_history-compatible: it folds into the
    # trajectory as a canary (cpu-forced), never a baseline
    bh = _import_tool("bench_history")
    rows = bh.collect([str(tmp_path / "BENCH_manual_r07.json")])
    assert rows[0]["metrics"]["value"] == 123.0
    assert rows[0]["canary"] == "cpu-forced"
    # the serve leg's parsed line landed as SERVE_manual_rN.json and
    # folds into the trajectory under the serve context
    assert (tmp_path / "SERVE_manual_r07.json").exists()
    srows = bh.collect([str(tmp_path / "SERVE_manual_r07.json")])
    assert srows[0]["context"][0] == "serve"
    assert srows[0]["metrics"]["serve_rows_per_s"] == 9000.0
    assert srows[0]["metrics"]["serve_p99_ms"] == 12.0
    # the explain-heavy leg landed as its own artifact, and the mixed
    # leg's TreeSHAP p99 trends through bench_history
    assert (tmp_path / "SERVE_explain_manual_r07.json").exists()
    xrows = bh.collect([str(tmp_path / "SERVE_explain_manual_r07.json")])
    assert xrows[0]["metrics"]["serve_explain_p99_ms"] == 48.0
    # the ingest leg (--no-write) landed as the window-owned
    # INGEST_manual_rN.json and trends under its own ingest context
    assert (tmp_path / "INGEST_manual_r07.json").exists()
    irows = bh.collect([str(tmp_path / "INGEST_manual_r07.json")])
    assert irows[0]["context"][0] == "ingest"
    assert irows[0]["metrics"]["ingest_rows_per_s"] == 250000.0


def test_tpu_window_leg_triage_classes(tmp_path):
    """ISSUE 17 wedge triage: every non-clean leg gets one of the four
    classes; a fully clean window gets no triage block at all."""
    tw = _import_tool("tpu_window")
    clean = {"rc": 0, "parsed": {"backend": "tpu"}}
    assert tw.leg_triage(clean) is None
    # green-but-on-CPU is only a finding on a real (non-dry) window
    cpu = {"rc": 0, "parsed": {"backend": "cpu"}}
    assert tw.leg_triage(cpu) == "cpu-fallback"
    assert tw.leg_triage(cpu, dry_run=True) is None
    assert tw.leg_triage({"rc": -1, "tail": []}) == "timeout"
    assert tw.leg_triage({"rc": 1, "wedge_class": "transient",
                          "tail": []}) == "backend-wedge"
    # no wedge_class recorded, but the tail still smells like a wedge
    assert tw.leg_triage({"rc": 1, "tail": ["...", "backend wedge "
                          "detected"]}) == "backend-wedge"
    assert tw.leg_triage({"rc": 1, "tail": ["ValueError: bad "
                          "param"]}) == "failure"

    results = {"bench": {"rc": -1, "tail": []},
               "bench_serve": {"rc": 1, "wedge_class": "transient",
                               "tail": []},
               "trace": {"rc": 0, "parsed": {}}}
    tri = tw.triage_legs(results)
    assert tri["legs"] == {"bench": "timeout",
                           "bench_serve": "backend-wedge"}
    assert tri["classes"] == ["backend-wedge", "timeout"]
    assert tw.triage_legs({"trace": {"rc": 0, "parsed": {}}}) is None

    # bench_history surfaces the block in the round's note
    rec = {"round": 3, "timestamp": "2026-08-07T00:00:00",
           "backend": "cpu (forced)", "dry_run": True,
           "parsed": None, "triage": tri, "legs": results}
    p = tmp_path / "BENCH_manual_r03.json"
    p.write_text(json.dumps(rec))
    bh = _import_tool("bench_history")
    rows = bh.collect([str(p)])
    assert rows[0]["triage"] == tri["legs"]
    assert "triage[bench:timeout, bench_serve:backend-wedge]" \
        in rows[0]["note"]


def test_tpu_window_dry_run_end_to_end(tmp_path):
    """Acceptance: `tpu_window.py --dry-run` executes real capture legs
    on CPU and emits a well-formed BENCH_manual artifact + health
    summary.  Restricted to the bench + trace legs to bound wall clock
    (the stubbed test above covers the full leg set)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "tpu_window.py"),
         "--dry-run", "--out", str(tmp_path), "--legs", "bench,trace",
         "--leg-timeout", "420"],
        capture_output=True, text=True, timeout=500, env=env)
    assert r.returncode == 0, r.stdout + r.stderr
    rec = json.loads((tmp_path / "BENCH_manual_r01.json").read_text())
    assert rec["dry_run"] is True
    assert rec["parsed"]["backend"] == "cpu-forced"
    assert rec["parsed"]["value"] > 0
    # the bench line certifies itself: health ran and found nothing
    assert rec["parsed"]["health_checks"] > 0
    assert rec["parsed"]["health_failures"] == 0
    assert rec["legs"]["trace"]["rc"] == 0
    assert rec["trace_files"] > 0, "jax.profiler trace left no artifact"
    health = json.loads((tmp_path / "HEALTH_manual_r01.json").read_text())
    assert health["verdict"] == "healthy"
    assert health["events_ok"] is True
    assert health["legs"]["bench"]["health"]["fingerprints"] > 0
