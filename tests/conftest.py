"""Test configuration: force an 8-device virtual CPU mesh so sharding tests
run without TPU hardware (SURVEY.md §4's loopback-collective gap).

The container's sitecustomize imports jax and registers the axon TPU plugin
before pytest starts, so setting env vars alone is too late — the jax config
must be updated directly (safe: no backend is initialized yet at conftest
import time).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------
# Quick/slow tiers (round-5): the full suite is ~29 min on a 1-CPU
# container (jit-compile bound); `pytest -m quick` runs the <6.5s tests
# (~4-5 min), `pytest -m slow` the compile-heavy rest, plain `pytest`
# everything.  The list is data (measured durations), not decorators —
# re-measure with `pytest --durations=80` and update when it drifts.
_SLOW = {
    "test_xprof.py::test_e2e_capture_parse_attribute",
    "test_rank.py::test_lambdarank_example_parity",
    "test_cli.py::test_reference_example_confs_run_unchanged[multiclass_classification-multi_logloss]",
    "test_train.py::test_reference_parity_binary",
    "test_bundling.py::test_training_metrics_unchanged_vs_no_bundle",
    "test_continued.py::test_continue_training_from_reference_model",
    "test_cli.py::test_reference_example_confs_run_unchanged[lambdarank-ndcg@3]",
    "test_model_io.py::test_reference_cli_loads_our_model",
    "test_sparse.py::test_wide_sparse_constructs_and_trains",
    "test_distributed.py::test_two_process_data_parallel_bitmatch",
    "test_predict_device.py::test_device_predict_matches_host_multiclass_categorical",
    "test_cli.py::test_reference_example_confs_run_unchanged[regression-l2]",
    "test_bundling.py::test_bundled_dataset_voting_parallel_full_vote_matches_data",
    "test_cegb.py::test_reference_cli_cegb_parity",
    "test_parallel.py::test_goss_and_bagging_under_data_parallel",
    "test_parallel.py::test_tree_learner_data_trains_end_to_end",
    "test_cli.py::test_init_score_sidecar_and_param",
    "test_sklearn.py::test_sklearn_clone_and_grid_search",
    "test_rank.py::test_lambdarank_mslr_shaped_no_recompile",
    "test_bundling.py::test_wave_grower_bundled_matches_serial",
    "test_sklearn.py::test_classifier_multiclass",
    "test_bundling.py::test_bundled_dataset_with_parallel_learner",
    "test_bundling.py::test_bundled_predict_device_matches_host",
    "test_cli.py::test_cli_snapshots_and_continue",
    "test_continued.py::test_init_model_multiclass",
    "test_cli.py::test_multi_error_top_k",
    "test_bundling.py::test_bundled_voting_tight_gate_no_phantom_splits",
    "test_wave.py::test_mixed_width_wave_matches_serial",
    "test_forced_splits.py::test_reference_cli_forced_splits_parity",
    "test_train.py::test_dart_and_goss_compose_with_bundling_and_categoricals",
    "test_train.py::test_multiclass",
    "test_parallel.py::test_tree_learner_feature_trains_end_to_end",
    "test_cegb.py::test_coupled_penalty_narrows_feature_set",
    "test_categorical.py::test_wave_categorical_matches_serial",
    "test_api_extras.py::test_pandas_categorical_roundtrip",
    "test_cegb.py::test_tradeoff_split_scaling_equality",
    "test_dump_model.py::test_if_else_code_compiles_and_matches[3]",
    "test_continued.py::test_init_model_with_now_trivial_feature",
    "test_wave.py::test_wave_gated_boosting_matches_serial_loss",
    "test_cli.py::test_cli_task_refit",
    "test_cli.py::test_cli_predict_from_model_file_only",
    "test_categorical.py::test_high_cardinality_categorical_uint16_path",
    "test_continued.py::test_refit_moves_leaf_values_toward_new_data",
    "test_bundling.py::test_reference_cli_efb_auc_parity",
    "test_cegb.py::test_split_penalty_prunes_splits",
    "test_cli.py::test_cli_train_predict_matches_python_api",
    "test_categorical.py::test_categorical_train_roundtrip_and_predict",
    "test_continued.py::test_init_model_file_roundtrip",
    "test_categorical.py::test_categorical_device_replay_matches_host_predict",
    "test_sampling.py::test_feature_fraction_bynode_deterministic",
    "test_continued.py::test_init_model_booster_equals_uninterrupted",
    "test_predict_device.py::test_prediction_early_stop_converges_to_same_argmax",
    "test_predict_device.py::test_pred_early_stop_device_matches_host_multiclass",
    "test_predict_device.py::test_pred_early_stop_multiclass_differential",
    "test_predict_device.py::test_loaded_model_device_predict_matches_host",
    "test_dump_model.py::test_dump_model_walk_matches_predict",
    "test_parallel.py::test_data_parallel_matches_single_device",
    "test_train.py::test_jit_cache_reuses_compiled_growers",
    "test_parallel.py::test_feature_parallel_matches_single_device",
    "test_parallel.py::test_wave_data_parallel_matches_single_device",
    "test_api_extras.py::test_pandas_int_categories_json_roundtrip",
    "test_sampling.py::test_balanced_bagging_mask_respects_class_fractions",
    "test_wave.py::test_wave_capacity1_matches_serial",
    "test_cli.py::test_cli_overrides_beat_config_file",
    "test_predict_device.py::test_device_predict_matches_host_binary",
    "test_categorical.py::test_categorical_search_matches_reference_oracle[False-0]",
    "test_sklearn.py::test_early_stopping_eval_set",
    "test_wave.py::test_wave_pass_count_regression_guard",
    "test_obs.py::test_off_path_overhead_guard",
    "test_tools.py::test_tpu_window_dry_run_end_to_end",
    "test_tools.py::test_run_suite_reports_failure",
    "test_wave_apply.py::test_batched_apply_differential[categorical_bitset-7]",
    "test_wave_apply.py::test_batched_apply_differential[categorical_bitset-23]",
    "test_wave_apply.py::test_batched_apply_differential[tie_gain-7]",
    "test_wave_apply.py::test_batched_apply_differential[tie_gain-23]",
    "test_wave_apply.py::test_batched_apply_differential[bagging-7]",
    "test_wave_apply.py::test_batched_apply_differential[bagging-23]",
    "test_wave_apply.py::test_batched_apply_mesh_parallel",
    "test_hist_fused.py::test_fused_packed_differential[nan_default_left-7]",
    "test_hist_fused.py::test_fused_packed_differential[categorical_bitset-7]",
    "test_hist_fused.py::test_fused_packed_differential[categorical_bitset-23]",
    "test_hist_fused.py::test_mesh_data_parallel_packed_matches_single",
    "test_hist_fused.py::test_packed_capacity_cuts_waves",
    "test_hist_quant.py::test_quant_training_auc_budget",
    "test_hist_quant.py::test_overlap_bit_identical_to_serial_oracle",
    "test_hist_quant.py::test_quant_grid_differential[nan_default_left-7-int16]",
    "test_hist_quant.py::test_quant_grid_differential[categorical_bitset-7-int16]",
    "test_hist_quant.py::test_quant_grid_differential[nan_default_left-7-int8]",
    "test_hist_quant.py::test_quant_grid_differential[categorical_bitset-23-int8]",
    "test_hist_quant.py::test_resume_bit_identical_int16",
    "test_hist_quant.py::test_fused_grad_bit_identical_wave_path",
    "test_hist_quant.py::test_fused_grad_bit_identical_bagging",
    "test_hist_quant.py::test_quant_mesh_parity",
    "test_hist_quant.py::test_fused_grad_ineligible_paths",
    "test_explain.py::test_oracle_matches_brute_force_categorical_nan",
    "test_robust.py::test_resume_bit_identical_dart",
    "test_robust.py::test_resume_bit_identical_two_device_mesh",
    "test_robust.py::test_sigterm_checkpoints_and_resumes",
    "test_online.py::test_device_refit_matches_host_multiclass",
    "test_online.py::test_device_refit_matches_host_mesh_2dev",
    "test_online.py::test_device_refit_matches_host_binary[0.0]",
    "test_rank_device.py::test_rank_data_parallel_end_to_end",
    "test_rank_device.py::test_trainer_routes_device_score_to_ndcg",
    "test_rank_device.py::test_fused_rank_gradients_bit_identical",
    "test_rank_device.py::test_fused_rank_gradients_bit_identical_wave_interpret",
    "test_rank_device.py::test_sharded_rank_grads_match_single_device_oracle[2]",
    "test_rank_device.py::test_sharded_rank_grads_match_single_device_oracle[3]",
    "test_serve.py::test_session_rank_topk_concurrent_mixed_sizes",
    "test_explain.py::test_session_explain_rank_model_parity",
}


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: compile-heavy test (>6.5s)")
    config.addinivalue_line("markers", "quick: fast tier (everything else)")


import pytest as _pytest_mod


@_pytest_mod.fixture(autouse=True)
def _flight_dumps_to_tmp(tmp_path, monkeypatch):
    """The flight recorder (obs/spans.py) dumps FLIGHT_rN.json on
    degradations and health aborts — several tests trigger those on
    purpose.  Default the dump dir to the test's tmp dir so no test can
    litter the repo root (a test that asserts on the dump location sets
    LGBM_TPU_FLIGHT_DIR itself and wins, monkeypatch being per-test)."""
    monkeypatch.setenv("LGBM_TPU_FLIGHT_DIR", str(tmp_path))


def pytest_collection_modifyitems(config, items):
    import pytest as _pytest
    tests_root = config.rootpath / "tests"
    for item in items:
        # file path relative to tests/ + test name (params included) —
        # resolved from item.path, not nodeid string surgery, so nested
        # dirs or odd invocation roots can't silently mis-tier into quick
        try:
            rel = item.path.relative_to(tests_root).as_posix()
        except ValueError:  # collected from outside tests/ (plugins)
            rel = item.path.name
        nid = f"{rel}::{item.name}"
        if nid in _SLOW:
            item.add_marker(_pytest.mark.slow)
        else:
            item.add_marker(_pytest.mark.quick)
