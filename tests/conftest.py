"""Test configuration: force an 8-device virtual CPU mesh so sharding tests
run without TPU hardware (SURVEY.md §4's loopback-collective gap).

The container's sitecustomize imports jax and registers the axon TPU plugin
before pytest starts, so setting env vars alone is too late — the jax config
must be updated directly (safe: no backend is initialized yet at conftest
import time).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
