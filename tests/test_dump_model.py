"""dump_model (JSON) and model_to_if_else (codegen) tests.

The generated C is actually compiled (gcc is in the image) and its
predictions compared against Booster.predict — stronger than the
reference's own string-only tests (reference: tree.h:177-183,
gbdt_model_text.cpp:20-270).
"""
import ctypes
import json
import os
import subprocess

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _train(rounds=8, num_class=None, cat=None, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(800, 5))
    if cat:
        X[:, cat] = rng.integers(0, 8, size=(800, len(cat)))
    if num_class:
        y = rng.integers(0, num_class, size=800).astype(np.float64)
        params = {"objective": "multiclass", "num_class": num_class}
    else:
        y = (X[:, 0] + X[:, 1] > 0).astype(np.float64)
        params = {"objective": "binary"}
    params.update({"num_leaves": 15, "verbose": -1, "min_data_in_leaf": 5})
    ds = lgb.Dataset(X, label=y, categorical_feature=cat or "auto",
                     params=params)
    return lgb.train(params, ds, num_boost_round=rounds), X


def test_dump_model_structure():
    bst, X = _train()
    d = bst.dump_model()
    assert d["name"] == "tree"
    assert d["version"] == "v3"
    assert d["num_class"] == 1
    assert d["num_tree_per_iteration"] == 1
    assert d["max_feature_idx"] == 4
    assert d["objective"].startswith("binary")
    assert len(d["tree_info"]) == 8
    t0 = d["tree_info"][0]
    assert set(t0) == {"tree_index", "num_leaves", "num_cat", "shrinkage",
                       "tree_structure"}
    root = t0["tree_structure"]
    assert root["decision_type"] == "<="
    assert {"split_feature", "threshold", "left_child", "right_child",
            "internal_count"} <= set(root)
    # leaves carry values that round-trip through json
    json.dumps(d)
    assert d["feature_importances"]


def _walk(node, row):
    while "leaf_value" not in node:
        f = node["split_feature"]
        v = row[f]
        if node["decision_type"] == "==":
            cats = [int(c) for c in str(node["threshold"]).split("||")]
            go_left = (not np.isnan(v)) and v >= 0 and int(v) in cats
        else:
            if np.isnan(v):
                go_left = node["default_left"] \
                    if node["missing_type"] == "NaN" else \
                    (0.0 <= node["threshold"])
            else:
                go_left = v <= node["threshold"]
        node = node["left_child"] if go_left else node["right_child"]
    return node["leaf_value"]


def test_dump_model_walk_matches_predict():
    bst, X = _train(cat=[4], seed=2)
    d = bst.dump_model()
    raw = bst.predict(X[:50], raw_score=True)
    for i in range(50):
        s = sum(_walk(t["tree_structure"], X[i]) for t in d["tree_info"])
        assert abs(s - raw[i]) < 1e-6, i


@pytest.mark.parametrize("num_class", [None, 3])
def test_if_else_code_compiles_and_matches(tmp_path, num_class):
    bst, X = _train(num_class=num_class, seed=3)
    code = bst.model_to_if_else()
    src = tmp_path / "model.c"
    src.write_text(code)
    so = tmp_path / "model.so"
    subprocess.run(["gcc", "-O1", "-shared", "-fPIC", "-o", str(so),
                    str(src), "-lm"], check=True)
    lib = ctypes.CDLL(str(so))
    K = num_class or 1
    lib.PredictRaw.argtypes = [ctypes.POINTER(ctypes.c_double),
                               ctypes.POINTER(ctypes.c_double)]
    raw = bst.predict(X[:30], raw_score=True)
    out = (ctypes.c_double * K)()
    for i in range(30):
        row = (ctypes.c_double * X.shape[1])(*X[i])
        lib.PredictRaw(row, out)
        got = np.asarray(out[:K])
        want = np.atleast_1d(raw[i])
        np.testing.assert_allclose(got, want, atol=1e-6)


def test_cli_convert_model(tmp_path):
    bst, X = _train()
    model = tmp_path / "model.txt"
    bst.save_model(str(model))
    out = tmp_path / "pred.c"
    from lightgbm_tpu.app import main
    main([f"task=convert_model", f"input_model={model}",
          f"convert_model={out}"])
    assert "PredictRaw" in out.read_text()


def test_loaded_booster_importance_and_dump(tmp_path):
    """File-loaded boosters expose the same windowed importance surface
    (regression: LoadedGBDT.feature_importance signature drift)."""
    import numpy as np
    rng = np.random.default_rng(11)
    X = rng.normal(size=(400, 6))
    y = (X[:, 0] > 0).astype(np.float64)
    p = {"objective": "binary", "num_leaves": 7, "verbose": -1,
         "min_data_in_leaf": 5}
    bst = lgb.train(p, lgb.Dataset(X, label=y, params=p), 4)
    path = str(tmp_path / "m.txt")
    bst.save_model(path)
    re = lgb.Booster(model_file=path)
    imp = re.feature_importance()
    assert imp.sum() > 0
    np.testing.assert_array_equal(imp, bst.feature_importance())
    d = re.dump_model()
    assert len(d["tree_info"]) == 4
