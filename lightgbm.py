"""Drop-in import shim: ``import lightgbm as lgb`` resolves to the
TPU-native framework, so reference scripts and the reference's
``examples/python-guide`` run without edits.

Everything is re-exported from :mod:`lightgbm_tpu`; see that package for
the actual implementation.  If the real LightGBM wheel is ever installed
in the same environment it will shadow or be shadowed by this module
depending on ``sys.path`` order — this repo's image does not ship it.
"""
from lightgbm_tpu import *  # noqa: F401,F403
from lightgbm_tpu import __version__, basic, callback, compat, engine, plotting, sklearn  # noqa: F401

try:  # mirror the reference's submodule layout for qualified imports
    from lightgbm_tpu import capi as c_api  # noqa: F401
except ImportError:  # pragma: no cover
    pass
