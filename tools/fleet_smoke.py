"""Elastic-fleet smoke — the ``fleet`` suite tier (ISSUE 20).

Gang-launches REAL 3-process training fleets over the host-TCP
transport (the CI twin of ``jax.distributed``) and proves the elastic
plane end to end on CPU:

- **plain / bagging / ranking bit-exact**: a 3-rank fleet trains
  bit-identically (tree sections) to the single-process oracle on a
  plain regression fixture, a bagging binary fixture, and a lambdarank
  fixture with a ``.query`` sidecar (query-aligned row shards);
- **healthy path is quiet**: the plain run's event trail carries no
  deaths, resizes, or stall stamps — zero new sync points;
- **kill-one-rank recovery**: a rank hard-killed mid-iteration
  (``fleet_die`` injection) is detected via the heartbeat transport,
  survivors roll back to the last common checkpoint and resume, the
  healed joiner folds in, the run completes, and the final model still
  bit-matches the never-failed oracle.

Writes ``FLEET_rN.json`` (fleet_ranks / fleet_recoveries series for
``tools/bench_history.py``).  Last stdout line is the
``{"ok": ..., "checks": ...}`` verdict map (the tools/run_suite.py
tool-tier contract).  Exit 0 iff all pass.

    python tools/fleet_smoke.py --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}
RANKS = 3


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _next_round(out_dir):
    n = 0
    for f in glob.glob(os.path.join(out_dir, "FLEET_r*.json")):
        m = re.search(r"FLEET_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def _tree_text(path):
    with open(path) as fh:
        return fh.read().split("\nparameters:\n")[0]


def _write_fixtures(art):
    """Three fixtures: plain regression, bagging binary, lambdarank
    with a ``.query`` sidecar (the query-aligned shard path)."""
    rng = np.random.default_rng(3)
    X = rng.normal(size=(120, 5))
    y = X[:, 0] * 2.0 + np.sin(X[:, 1]) + rng.normal(scale=0.1, size=120)
    plain = os.path.join(art, "plain.tsv")
    np.savetxt(plain, np.column_stack([y, X]), delimiter="\t", fmt="%.8f")

    yb = (X[:, 0] + 0.4 * X[:, 1] > 0).astype(np.float64)
    bag = os.path.join(art, "bag.tsv")
    np.savetxt(bag, np.column_stack([yb, X]), delimiter="\t", fmt="%.8f")

    qsizes = rng.integers(5, 12, size=14)
    n = int(qsizes.sum())
    Xr = rng.normal(size=(n, 5))
    yr = rng.integers(0, 4, size=n).astype(np.float64)
    rank = os.path.join(art, "rank.tsv")
    np.savetxt(rank, np.column_stack([yr, Xr]), delimiter="\t", fmt="%.8f")
    np.savetxt(rank + ".query", qsizes, fmt="%d")
    return {"plain": plain, "bag": bag, "rank": rank}


def _oracle(params, out_path):
    """Never-failed single-process run of the same training args (own
    process, so its jax state cannot leak into the fleet ranks')."""
    p = {k: v for k, v in params.items() if not k.startswith("tpu_fleet")}
    p["output_model"] = out_path
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    env.pop("LGBM_TPU_FAULTS", None)
    subprocess.run([sys.executable, "-m", "lightgbm_tpu",
                    *[f"{k}={v}" for k, v in p.items()]],
                   check=True, env=env, capture_output=True, timeout=300)
    return _tree_text(out_path)


def _events(fleet_dir):
    from lightgbm_tpu.fleet.launch import EVENTS
    path = os.path.join(fleet_dir, EVENTS)
    if not os.path.exists(path):
        return []
    return [json.loads(line) for line in open(path)]


def run_smoke(out_dir=REPO, write=True) -> dict:
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.fleet.launch import launch_fleet

    t0 = time.time()
    art = tempfile.mkdtemp(prefix="fleet_smoke_")
    data = _write_fixtures(art)
    recoveries = 0

    def fleet_params(tag, data_path, **extra):
        p = {"task": "train", "objective": "regression",
             "data": data_path, "label_column": "0",
             "num_iterations": "10", "num_leaves": "7",
             "min_data_in_leaf": "5", "learning_rate": "0.1",
             "tpu_ingest": "true", "verbosity": "-1",
             "tpu_fleet": str(RANKS), "tpu_fleet_heartbeat_s": "15",
             "tpu_fleet_dir": os.path.join(art, f"fd_{tag}"),
             "output_model": os.path.join(art, f"{tag}.txt")}
        p.update({k: str(v) for k, v in extra.items()})
        return p

    def bitmatch_leg(tag, p, per_rank_env=None):
        res = launch_fleet(Config.from_params(p), p,
                           per_rank_env=per_rank_env)
        oracle = _oracle(p, os.path.join(art, f"oracle_{tag}.txt"))
        exact = (res["rc"] == 0
                 and _tree_text(p["output_model"]) == oracle)
        return res, exact

    # ---- plain regression: bit-exact AND a quiet event trail --------
    p = fleet_params("plain", data["plain"])
    try:
        res, exact = bitmatch_leg("plain", p)
        check("fleet.plain.bit_exact", res["ok"] and exact, res)
        noisy = [e for e in _events(p["tpu_fleet_dir"])
                 if e["name"] in ("member_dead", "resize", "fleet_stall")]
        check("fleet.plain.healthy_path_quiet", not noisy, noisy)
    except Exception as exc:  # noqa: BLE001
        check("fleet.plain.bit_exact", False, repr(exc))
        CHECKS.setdefault("fleet.plain.healthy_path_quiet", False)

    # ---- bagging: the seeded row subsampling replays identically ----
    p = fleet_params("bag", data["bag"], objective="binary",
                     bagging_fraction="0.8", bagging_freq="2", seed="7")
    try:
        res, exact = bitmatch_leg("bag", p)
        check("fleet.bagging.bit_exact", res["ok"] and exact, res)
    except Exception as exc:  # noqa: BLE001
        check("fleet.bagging.bit_exact", False, repr(exc))

    # ---- lambdarank: .query sidecar -> query-aligned shards ---------
    p = fleet_params("rank", data["rank"], objective="lambdarank")
    try:
        res, exact = bitmatch_leg("rank", p)
        check("fleet.ranking.bit_exact", res["ok"] and exact, res)
    except Exception as exc:  # noqa: BLE001
        check("fleet.ranking.bit_exact", False, repr(exc))

    # ---- kill one rank mid-iteration: detect, roll back, heal, finish
    p = fleet_params("kill", data["plain"], num_iterations="12",
                     tpu_fleet_heartbeat_s="3", tpu_checkpoint_freq="4")
    try:
        res, exact = bitmatch_leg("kill", p, per_rank_env={
            1: {"LGBM_TPU_FAULTS": "fleet_die:raise@iter=6"}})
        ev = [e["name"] for e in _events(p["tpu_fleet_dir"])]
        recoveries = res["heals"]
        check("fleet.kill.recovers_and_completes",
              res["ok"] and res["rcs"].get(1) == 137
              and "member_dead" in ev and "resize" in ev, res)
        check("fleet.kill.bit_exact_vs_never_failed", exact)
    except Exception as exc:  # noqa: BLE001
        check("fleet.kill.recovers_and_completes", False, repr(exc))
        CHECKS.setdefault("fleet.kill.bit_exact_vs_never_failed", False)

    record = {
        "kind": "fleet",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "fleet_ranks": RANKS,
        "fleet_recoveries": int(recoveries),
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "artifacts_dir": art,
    }
    if write:
        n = _next_round(out_dir)
        path = os.path.join(out_dir, f"FLEET_r{n:02d}.json")
        with open(path, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# wrote {path}")
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="3-process elastic-fleet smoke (fleet suite tier)")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    ap.add_argument("--out", default=REPO,
                    help="FLEET_rN.json artifact dir (default: repo root)")
    ap.add_argument("--no-write", action="store_true",
                    help="skip writing the FLEET_rN.json artifact")
    args = ap.parse_args(argv)
    record = run_smoke(out_dir=args.out, write=not args.no_write)
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
