"""Generate docs/PARAMETERS.md from the Config dataclass + alias table.

The reference generates Parameters.rst from config.h with
helpers/parameter_generator.py — one annotated source of truth.  This is
the same property for the TPU build: ``lightgbm_tpu/config.py`` defines
every field, default, and alias; this script renders them, grouped by the
dataclass's section comments, with inline ``#`` comments as descriptions.

Run: python tools/gen_param_docs.py   (rewrites docs/PARAMETERS.md)
"""
from __future__ import annotations

import inspect
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu import config as cfgmod
from lightgbm_tpu.config import _ALIASES, _MULTI_VALUE, Config


def parse_sections():
    """(section, name, default_repr, comment) in declaration order."""
    src = inspect.getsource(Config)
    section = "Other"
    rows = []
    for line in src.splitlines():
        s = line.strip()
        m = re.match(r"# ---- (.+?) ----", s)
        if m:
            section = m.group(1)
            continue
        m = re.match(r"(\w+):\s*[\w\[\]\.]+\s*=\s*(.+?)(?:\s*#\s*(.*))?$", s)
        if m and not s.startswith("#"):
            name, default, comment = m.groups()
            default = default.strip()
            if default.startswith("field(default_factory=list)"):
                default = "[]"
            elif "default_factory=lambda" in default:
                inner = re.search(r"lambda:\s*(.+?)\)\s*$", default)
                default = inner.group(1) if inner else default
            rows.append((section, name, default, comment or ""))
    return rows


# Process-level switches living outside the Config surface (they must work
# before any Config exists — at import time).  Rendered as their own
# section so the generated doc stays the one place parameters live.
ENV_VARS = [
    ("LGBM_TPU_TIMETAG",
     "set to `1` to accumulate per-phase wall times (binning, boosting, "
     "tree growth, score update, predict) and print them at process exit "
     "— the reference's compiled-in `TIMETAG` analog.  Synchronizes the "
     "device after each phase, so throughput drops while it is on."),
    ("LGBM_TPU_TELEMETRY",
     "path of the structured telemetry sink: a directory (per-process "
     "`telemetry.{process_index}.jsonl` files inside it) or a `*.jsonl` "
     "file.  Streams JSONL events — one `iteration` record per boosting "
     "iteration (phase timings, train/valid metrics, leaves, wave count, "
     "counter snapshots, recompile deltas), `collective` records for "
     "psum/all_gather traffic, and an atexit `summary`.  Merge with "
     "`python tools/telemetry_report.py <path>`.  Equivalent to the "
     "`tpu_telemetry` parameter.  Implies the same per-phase device "
     "synchronization as `LGBM_TPU_TIMETAG`."),
    ("LGBM_TPU_PROFILE",
     "set to `1` for profile mode (equivalent to the `tpu_profile` "
     "parameter): every training phase and jitted `lgbm/*` unit is "
     "sync-bracketed and cost-analyzed — `kernel_profile` events carry "
     "XLA `cost_analysis()` FLOPs/bytes, achieved seconds, the "
     "analytical roofline seconds, and the achieved roofline fraction; "
     "`memory_census` events attribute live HBM bytes to logical "
     "buffers (binned matrix, scores, forest SoA, ...) and track the "
     "run peak; a release audit warns when a buffer expected to be "
     "consumed survives its phase.  Events need a telemetry sink "
     "configured; the aggregates land in the digest (and bench.py's "
     "`peak_hbm_bytes` / `kernel_roofline` fields) either way.  The "
     "gate is PROCESS-WIDE (like the telemetry sink): once on — via env "
     "or any Booster's `tpu_profile` — every later Booster is "
     "instrumented until `obs.enable_profile(False)`.  Profiling breaks "
     "async dispatch by design — never benchmark with it on."),
    ("LGBM_TPU_HEALTH",
     "training-health sentinels (equivalent to the `tpu_health` "
     "parameter): `monitor` (or `1`) finite-checks every iteration's "
     "gradients/hessians (attributed to the objective that produced "
     "them, plus GOSS's amplifier and DART's renormalized scores), "
     "split gains and leaf values (attributed to node + feature), and "
     "histogram-total conservation (leaf count/weight sums vs the "
     "root); emits `health` events on failure and per-iteration "
     "`fingerprint` events (cheap hash of the score vector + tree "
     "arrays, interval set by `tpu_fingerprint_freq`); under "
     "multi-process training the fingerprints are compared across "
     "ranks each iteration and a mismatch ABORTS with which-rank "
     "attribution (`divergence` event).  `strict` additionally aborts "
     "on the first numerics failure with a `TrainingHealthError` "
     "naming the phase/iteration (and node/feature).  PROCESS-WIDE "
     "once on, like the telemetry sink; checks synchronize the device "
     "each iteration, so expect a few percent overhead — off (unset) "
     "costs one boolean per check site.  `tools/tpu_window.py` runs "
     "every capture leg with `monitor` on so a TPU-window datapoint "
     "certifies itself."),
    ("LGBM_TPU_TRACE",
     "set to `1` for trace mode (equivalent to the `tpu_trace` "
     "parameter): the span layer (`obs/spans.py`) emits one `span` "
     "event per completed span — serving requests "
     "(queue→coalesce→pad→device-execute, trace_id minted at the HTTP "
     "edge from `X-Request-Id`) and training iterations (iteration + "
     "its phase timers) share the schema, so "
     "`python tools/trace_export.py <telemetry path>` renders both on "
     "one Perfetto/Chrome timeline.  PROCESS-WIDE once on; like "
     "profile mode it sync-brackets phases — attribution runs only, "
     "never benchmarks."),
    ("LGBM_TPU_FLIGHT",
     "flight-recorder ring length (equivalent to the `tpu_flight_len` "
     "parameter, default 256; `0` disables): the last N spans + "
     "operational events (health, degradation, overload, iteration, "
     "serve batches) kept in memory with no telemetry sink needed, and "
     "dumped as `FLIGHT_rN.json` on a serve degradation flip, an "
     "overload storm, a `TrainingHealthError`/divergence abort, or on "
     "demand via `GET /debug/flight`.  `LGBM_TPU_FLIGHT_DIR` chooses "
     "the dump directory (default: the working directory)."),
    ("LGBM_TPU_TRAIN_METRICS",
     "train-side metrics exporter port (overrides the "
     "`tpu_train_metrics_port` parameter): `0` binds an ephemeral "
     "port, `N>0` binds `N + process_index` (each rank of a multi-host "
     "run exports locally without colliding), `off`/`false`/`-1` "
     "disarms.  While a train runs, `GET /metrics` serves the "
     "Prometheus exposition (iteration, ETA, cumulative "
     "`row_iters_per_s`, per-phase wall fractions, checkpoint age, "
     "watchdog/retry/stall counters, recompiles, collective bytes, "
     "straggler skew, measured-vs-model reconciliation ratios), "
     "`GET /progress` the JSON progress view (smoothed ETA, last-K "
     "iteration records, live `vs_baseline`), and `GET /debug/flight` "
     "the live flight ring.  `tools/train_watch.py <url>` tails it as "
     "a console view."),
    ("LGBM_TPU_SERVE_SLO_P99_MS",
     "serving-engine override for `tpu_serve_slo_p99_ms` — the p99 "
     "latency objective the `/metrics` + `/health` SLO-burn gauge "
     "measures against (over-target fraction of recent requests "
     "divided by the 1% budget a p99 objective allows; 1.0 = burning "
     "budget exactly at the allowed rate)."),
    ("LGBM_TPU_SERVE_AOT_DIR",
     "AOT executable store directory (overrides the "
     "`tpu_serve_aot_dir` parameter; `serve/aot.py`).  When set, every "
     "pow2-bucket executable a `PredictorSession` (or the arena) "
     "compiles is serialized there, keyed by kind | backend platform | "
     "jax version | row bucket | forest-content digest — a later "
     "process with the same model boots from the store and serves "
     "request #1 with zero JIT compiles (`serve_coldstart_ms` in "
     "`SERVE_rN.json` measures the A/B).  Stale, corrupt, or "
     "cross-backend entries fall back to JIT loudly (`aot_fallback` "
     "flight event + `serve/aot_fallbacks` counter) with bit-identical "
     "output.  `tpu_serve_aot=false` disarms the store entirely."),
    ("LGBM_TPU_COMPILE_CACHE",
     "directory for JAX's persistent XLA compilation cache (equivalent "
     "to the `tpu_compile_cache_dir` parameter; see "
     "`lightgbm_tpu/utils/compile_cache.py`).  Compiled growers are "
     "content-addressed and survive process restarts, so steady-state "
     "reruns skip the multi-second cold compile (`bench.py` records "
     "`compile_cache_dir`/`compile_cache_warm` in its JSON line so a "
     "compile_s figure says which kind of compile it measured).  Must "
     "be set before the first `jit` compilation it should capture; "
     "enabling is best-effort (a cache failure never aborts training)."),
    ("LGBM_TPU_XPROF",
     "measured-roofline capture window (overrides the `tpu_xprof` / "
     "`tpu_xprof_iters` parameters; `obs/xprof.py`): `1`/`true` arms a "
     "windowed `jax.profiler` trace around `tpu_xprof_iters` (default "
     "3) mid-train iterations — warmup/compile iterations are skipped "
     "— a number > 1 sets the window length directly, and `0`/`off` "
     "disarms even when the parameter is set.  When the window closes "
     "the trace artifacts are parsed (stdlib-only Chrome-trace reader), "
     "device-op durations are bucketed by the `lgbm/*` scopes plus an "
     "`unattributed` residual, and the attribution joins the analytic "
     "cost models (`wave_kernel_cost`/`partition_cost`/"
     "`rank_pair_cost`/`shap_cost`) into `kernel_measured` events and "
     "the digest's measured-roofline table (see ROOFLINE.md).  Arming "
     "also installs the compile observer: per-jit backend-compile "
     "walls, persistent-cache hit/miss counts and retrace attribution "
     "as `compile` events, digest lines, and board `/metrics` gauges.  "
     "Works on any backend; capture adds profiler overhead INSIDE the "
     "window only (off-window step cost is guarded < 5% by "
     "`tools/xprof_smoke.py`)."),
    ("LGBM_TPU_XPROF_DIR",
     "where the capture window writes its trace artifacts (default: an "
     "`xprof` sibling of the telemetry sink, or a tempdir when no sink "
     "is configured).  The parsed per-kernel attribution records the "
     "directory in the digest so a window's raw artifacts can be "
     "re-read later (e.g. `tools/tpu_window.py`'s trace leg parses its "
     "own capture and embeds the table into `BENCH_manual_rN`)."),
    ("LGBM_TPU_SERVE_MAX_BATCH",
     "serving-engine override for `tpu_serve_max_batch` (the per-batch "
     "row cap of `serve.PredictorSession`); lets an operator retune a "
     "running deployment's batching without editing model/config files. "
     "`LGBM_TPU_SERVE_MAX_WAIT_MS` and `LGBM_TPU_SERVE_QUEUE_DEPTH` "
     "override the matching `tpu_serve_*` parameters the same way; an "
     "explicit constructor argument still wins over the env var."),
    ("LGBM_TPU_SERVE_MAX_WAIT_MS",
     "serving-engine override for `tpu_serve_max_wait_ms` — the longest "
     "the microbatcher holds the oldest queued request while coalescing "
     "(the latency knob of the latency/throughput trade)."),
    ("LGBM_TPU_SERVE_QUEUE_DEPTH",
     "serving-engine override for `tpu_serve_queue_depth` — the queued-"
     "row bound after which `submit` fails fast with an overload error "
     "(explicit backpressure instead of unbounded buffering)."),
    ("LGBM_TPU_FAULTS",
     "deterministic fault-injection spec (robust/faults.py) — "
     "`point:action[@cond[&cond...]]` legs separated by `;`.  Points: "
     "`device_execute`, `gradients`, `collective`, `serve_device`, "
     "`serve_explain_submit`, `serve_explain_device`, `serve_replica` "
     "(plus per-replica `serve_replica_{i}`), `serve_swap`, "
     "`serve_canary`, `checkpoint_write`, `online_ingest`, "
     "`online_refit`, `online_swap`.  Actions: `raise` (fatal), "
     "`transient` (the watchdog's retry path), `sleep=S` (stall the "
     "step), `hang`.  Conds: `iter=N` (boosting iteration), `call=N` "
     "(N-th check at that point), `p=F` (seeded probability), `n=N` "
     "(fire at most N times, default 1, -1 = always).  Example: "
     "`device_execute:transient@iter=3&n=2;serve_device:raise`.  Used "
     "by the `tools/fault_matrix.py` and `tools/chaos_serve.py` suite "
     "tiers to prove every recovery branch on CPU."),
    ("LGBM_TPU_FAULTS_SEED",
     "seed for the fault harness's probabilistic conds (`p=`); the same "
     "spec + seed replays the identical fault schedule (default 0)."),
    ("LGBM_TPU_FORCE_WAVE",
     "test hook: set to `interpret` to route the serial grower through "
     "the wave pipeline with the Pallas INTERPRETER on any backend, so "
     "CPU CI trains end to end through the packed/fused/quantized/"
     "overlap kernel path (tests/test_hist_quant.py's AUC-budget and "
     "resume differentials ride it).  Orders of magnitude slower than "
     "both the XLA fallback and a real TPU — never benchmark with it."),
    ("LGBM_TPU_EXPLAIN",
     "serving-engine override for `tpu_explain` — set to `0`/`false` to "
     "remove `POST /explain` and `PredictorSession.explain()` from a "
     "running deployment (the endpoint answers 404, the session raises), "
     "or `1` to force it on.  The TreeSHAP forest pack (per-node cover "
     "counts + path metadata) is built lazily on the first explain call "
     "either way, so predict-only sessions never pay the HBM cost."),
    ("LGBM_TPU_EXPLAIN_MAX_BATCH",
     "serving-engine override for `tpu_explain_max_batch` — the row cap "
     "of the explain plane's OWN microbatcher and pow2 bucket family "
     "(compiles at most `ceil(log2(max_batch)) + 1` TreeSHAP kernel "
     "shapes, counted by the same recompile counter as predict's).  "
     "Kept separate from `tpu_serve_max_batch` because one explained "
     "row costs O(leaves x depth^2) where a predicted row costs "
     "O(depth)."),
    ("LGBM_TPU_EXPLAIN_MAX_WAIT_MS",
     "serving-engine override for `tpu_explain_max_wait_ms` — the "
     "longest the explain microbatcher holds the oldest queued request "
     "while coalescing."),
    ("LGBM_TPU_SERVE_REPROBE_S",
     "serving-engine override for `tpu_serve_reprobe_s` — seconds "
     "between device re-probes while a session is degraded to the host "
     "predictor; a successful probe flips `/health` back to `ok` "
     "(`0` disables, restoring the old one-way latch)."),
    ("LGBM_TPU_SERVE_REPLICAS",
     "serving-fleet override for `tpu_serve_replicas` — how many "
     "`PredictorSession` replicas each registered model version packs "
     "behind the failover router (per-device on a multi-chip host, "
     "thread-pool replicas on CPU).  One wedged replica then costs "
     "capacity, never availability (its circuit breaker opens and a "
     "half-open probe re-admits it when it recovers)."),
    ("LGBM_TPU_SERVE_ROLLBACK_WATCH_S",
     "serving-fleet override for `tpu_serve_rollback_watch_s` — how "
     "long after a hot-swap the registry watches the new live version's "
     "metrics (failed-request rate, degraded transitions, SLO burn) and "
     "rolls back AUTOMATICALLY to the still-resident previous version "
     "on a regression (`0` disables the watch; manual "
     "`POST /models/{name}/rollback` always works)."),
    ("LGBM_TPU_SERVE_SHED_LOW_FRAC",
     "serving-engine override for `tpu_serve_shed_low_frac` — the "
     "fraction of the queue-row budget low-priority requests may fill "
     "before overload sheds them (`Retry-After` on the 503; per-class "
     "served/shed counters in `/metrics`).  "
     "`LGBM_TPU_SERVE_SHED_NORMAL_FRAC` overrides the normal-priority "
     "budget the same way; high priority always owns the full queue."),
    ("LGBM_TPU_ONLINE_REFIT_EVERY",
     "online-loop override for `tpu_online_refit_every` — the row "
     "cadence of `task=online`'s refresh cycle (refit/continue + "
     "canary-gated swap every N freshly ingested labeled rows); lets "
     "an operator retune a running loop's refresh rate without "
     "editing config files.  `LGBM_TPU_ONLINE_WINDOW` overrides "
     "`tpu_online_window` the same way."),
    ("LGBM_TPU_ONLINE_WINDOW",
     "online-loop override for `tpu_online_window` — the bounded "
     "ingest window: how many of the freshest labeled rows the loop "
     "keeps for the next refresh (older rows fall out; memory-bounded "
     "like the serve queue)."),
    ("LGBM_TPU_INGEST_CHUNK_ROWS",
     "streaming-ingestion override for `tpu_ingest_chunk_rows` — rows "
     "per streamed chunk for the array/`.npy`/`.npz`/LibSVM readers "
     "(the peak-raw-memory knob of `ingest/`); lets an operator retune "
     "a running pipeline's chunking without editing configs.  Chunk "
     "size never changes the constructed dataset (test-pinned), so it "
     "also sits in the checkpoint config-digest skip list."),
    ("LGBM_TPU_INGEST_MEMMAP",
     "streaming-ingestion override for `tpu_ingest_memmap` — back the "
     "binned matrix with an `np.memmap` file instead of host RAM: a "
     "directory (per-shard `X_bin.shardN.npy` inside) or a file path.  "
     "With it set, peak host RAM during ingestion is O(chunk + "
     "sample) even though the constructed dataset may be far larger."),
    ("LGBM_TPU_PREDICT_MIN_WORK",
     "CLI `task=predict` routing override: the rows x trees work "
     "threshold above which value predictions go through the serving "
     "session (device-resident forest, pow2 buckets) instead of the "
     "host loop.  `0` forces every predict through the session; a huge "
     "value forces the host loop.  Unset uses the booster's built-in "
     "dispatch-overhead heuristic."),
    ("LGBM_TPU_CONTRIB_MIN_WORK",
     "`predict_contrib` routing override: the rows x trees work "
     "threshold above which contribution requests go through the "
     "batched device TreeSHAP kernel (`explain/`) instead of the host "
     "oracle (`core/shap.py`).  `0` forces every contrib through the "
     "device kernel; a huge value forces the host oracle.  Unset uses "
     "the built-in threshold (50k), which keeps tiny ad-hoc calls off "
     "the compile path."),
    ("LGBM_TPU_DRIFT_SAMPLE_RATE",
     "drift-plane override for `tpu_drift_sample_rate` — the fraction "
     "of served feature rows the serve-side sketch samples (the "
     "prediction histogram always takes every response).  `1.0` "
     "sketches every batch — what the drift smoke pins; the default "
     "0.05 keeps the off-path overhead negligible.  "
     "`LGBM_TPU_DRIFT_CHECK_S`, `LGBM_TPU_DRIFT_MIN_ROWS` and "
     "`LGBM_TPU_DRIFT_PSI_WARN` override the cadence, the row floor "
     "and the breach threshold the same way; `LGBM_TPU_DRIFT=0` "
     "disarms the monitor entirely."),
    ("LGBM_TPU_QUALITY_WINDOW",
     "quality-plane override for `tpu_quality_window` — labeled rows "
     "per rolling evaluation window (the online loop's labeled stream "
     "feeds it).  `LGBM_TPU_QUALITY_DROP_WARN` overrides the windowed-"
     "AUC drop that counts as a breach."),
    ("LGBM_TPU_SERVE_ROLLBACK_ON_DRIFT",
     "registry override for `tpu_serve_rollback_on_drift` — opt a "
     "fleet into automatic post-swap rollback on a latched drift or "
     "quality breach.  Default off: breaches annotate the post-swap "
     "health report and dump the flight recorder, but never gate — "
     "drift is a property of TRAFFIC, and rolling back a good model "
     "because the world changed is usually wrong."),
    ("LGBM_TPU_FLEET",
     "elastic multi-host gang size (overrides the `tpu_fleet` "
     "parameter; `lightgbm_tpu/fleet/`).  `task=train` with a value "
     "N > 1 gang-launches N single-rank worker processes, rendezvoused "
     "through `rendezvous.json` in the fleet dir, and supervises them: "
     "liveness rides the fingerprint-gather cadence (zero extra sync "
     "points on the healthy path), a silent or dead rank is rolled "
     "back to the last common checkpoint and the survivors resume at "
     "the shrunk world, and (with `tpu_fleet_heal`) a replacement "
     "rank is relaunched and folds back in mid-run.  In the "
     "replicate-mode CI twin the final model is bit-identical to a "
     "single-process run at any world size.  Env overrides win over "
     "the config knobs so a CI wrapper can gang an unmodified "
     "params file."),
    ("LGBM_TPU_FLEET_HEARTBEAT_S",
     "override for `tpu_fleet_heartbeat_s` — the silence window "
     "(seconds, relative to each gather's first arrival) after which "
     "the coordinator classifies a rank dead and starts elastic "
     "recovery.  A rank merely lagging past half the window is "
     "stamped as a `fleet_stall` event but NOT killed."),
    ("LGBM_TPU_FLEET_TRANSPORT",
     "override for `tpu_fleet_transport`: `jax` forces "
     "`jax.distributed` device collectives, `host` forces the "
     "host-TCP coordinator (the CI twin that runs on CPU-only "
     "containers), `auto` (default) probes for cross-process device "
     "collective support and picks accordingly."),
    ("LGBM_TPU_FLEET_DIR",
     "override for `tpu_fleet_dir` — the rendezvous + fleet artifact "
     "directory (rendezvous address file, `fleet_events.jsonl` "
     "lifecycle trail, per-rank checkpoints, the `done.json` "
     "completion marker late joiners consult).  Default: a fresh "
     "`lgbm_tpu_fleet_*` temp directory per launch.  "
     "`LGBM_TPU_FLEET_RANK` / `LGBM_TPU_FLEET_JOIN` are internal "
     "per-worker stamps the launcher sets — setting them by hand "
     "makes a process act as a worker instead of the launcher."),
    ("LGBM_TPU_PEAK_FLOPS",
     "override the profile mode's device peak FLOP/s (used with "
     "`LGBM_TPU_PEAK_BW`) when the built-in per-chip table "
     "(`obs/profile.py DEVICE_PEAKS`) mispredicts the hardware."),
    ("LGBM_TPU_PEAK_BW",
     "override the profile mode's device peak HBM bytes/s."),
    ("JAX_PLATFORMS",
     "standard JAX backend selector (`cpu` forces the XLA host path)."),
]

PROFILER_NOTE = (
    "Profiler scope naming: every device phase is annotated for "
    "`jax.profiler` traces under the `lgbm/` prefix — host-side phases "
    "as `lgbm/<phase name>` (TraceAnnotation, e.g. `lgbm/tree growth`), "
    "compiled regions as XLA metadata scopes (`lgbm/hist_onehot`, "
    "`lgbm/hist_scatter`, `lgbm/hist_wave_xla`, `lgbm/pallas_hist`, "
    "`lgbm/pallas_hist_wave`, `lgbm/wave_hist`, `lgbm/wave_split_phase`, "
    "`lgbm/wave_partition`, `lgbm/split_scan`, `lgbm/tree_traverse`, "
    "`lgbm/forest_predict`, `lgbm/forest_leaf`).")


def main() -> None:
    rows = parse_sections()
    aliases = defaultdict(list)
    for a, canon in _ALIASES.items():
        aliases[canon].append(a)

    out = ["# Parameters", "",
           "Generated from `lightgbm_tpu/config.py` by "
           "`tools/gen_param_docs.py` — the single source of truth for "
           "names, defaults, and aliases (the analog of the reference's "
           "`Parameters.rst` generated from `config.h`). Parameter names "
           "and aliases match LightGBM v2.3.2; see `README.md` for the "
           "TPU-specific additions (`tpu_*`).", ""]
    cur = None
    for section, name, default, comment in rows:
        if section != cur:
            out += [f"## {section}", ""]
            cur = section
        bits = [f"- **`{name}`** = `{default}`"]
        if name in _MULTI_VALUE:
            bits.append("(comma-separated list)")
        if comment:
            bits.append(f"— {comment}")
        out.append(" ".join(bits))
        al = sorted(aliases.get(name, []))
        if al:
            out.append(f"  - aliases: " + ", ".join(f"`{a}`" for a in al))
    out += ["## Environment variables", ""]
    for name, desc in ENV_VARS:
        out.append(f"- **`{name}`** — {desc}")
    out += ["", PROFILER_NOTE]
    out.append("")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "PARAMETERS.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(out))
    print(f"wrote {path}: {sum(1 for r in rows)} parameters, "
          f"{sum(len(v) for v in aliases.values())} aliases")


if __name__ == "__main__":
    main()
