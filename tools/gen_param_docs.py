"""Generate docs/PARAMETERS.md from the Config dataclass + alias table.

The reference generates Parameters.rst from config.h with
helpers/parameter_generator.py — one annotated source of truth.  This is
the same property for the TPU build: ``lightgbm_tpu/config.py`` defines
every field, default, and alias; this script renders them, grouped by the
dataclass's section comments, with inline ``#`` comments as descriptions.

Run: python tools/gen_param_docs.py   (rewrites docs/PARAMETERS.md)
"""
from __future__ import annotations

import inspect
import os
import re
import sys
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from lightgbm_tpu import config as cfgmod
from lightgbm_tpu.config import _ALIASES, _MULTI_VALUE, Config


def parse_sections():
    """(section, name, default_repr, comment) in declaration order."""
    src = inspect.getsource(Config)
    section = "Other"
    rows = []
    for line in src.splitlines():
        s = line.strip()
        m = re.match(r"# ---- (.+?) ----", s)
        if m:
            section = m.group(1)
            continue
        m = re.match(r"(\w+):\s*[\w\[\]\.]+\s*=\s*(.+?)(?:\s*#\s*(.*))?$", s)
        if m and not s.startswith("#"):
            name, default, comment = m.groups()
            default = default.strip()
            if default.startswith("field(default_factory=list)"):
                default = "[]"
            elif "default_factory=lambda" in default:
                inner = re.search(r"lambda:\s*(.+?)\)\s*$", default)
                default = inner.group(1) if inner else default
            rows.append((section, name, default, comment or ""))
    return rows


def main() -> None:
    rows = parse_sections()
    aliases = defaultdict(list)
    for a, canon in _ALIASES.items():
        aliases[canon].append(a)

    out = ["# Parameters", "",
           "Generated from `lightgbm_tpu/config.py` by "
           "`tools/gen_param_docs.py` — the single source of truth for "
           "names, defaults, and aliases (the analog of the reference's "
           "`Parameters.rst` generated from `config.h`). Parameter names "
           "and aliases match LightGBM v2.3.2; see `README.md` for the "
           "TPU-specific additions (`tpu_*`).", ""]
    cur = None
    for section, name, default, comment in rows:
        if section != cur:
            out += [f"## {section}", ""]
            cur = section
        bits = [f"- **`{name}`** = `{default}`"]
        if name in _MULTI_VALUE:
            bits.append("(comma-separated list)")
        if comment:
            bits.append(f"— {comment}")
        out.append(" ".join(bits))
        al = sorted(aliases.get(name, []))
        if al:
            out.append(f"  - aliases: " + ", ".join(f"`{a}`" for a in al))
    out.append("")
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "PARAMETERS.md")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as fh:
        fh.write("\n".join(out))
    print(f"wrote {path}: {sum(1 for r in rows)} parameters, "
          f"{sum(len(v) for v in aliases.values())} aliases")


if __name__ == "__main__":
    main()
