"""Wave-grower cost decomposition — the supported attribution harness.

Promoted from the round-5 throwaway ``prof_decompose.py``: same four cost
hypotheses, now sharing the cost-model code the profile mode uses
(``obs.profile`` device peaks + ``ops.pallas_hist.wave_kernel_cost``), so
every leg prints measured time NEXT TO its analytical roofline and the
achieved fraction — the numbers ``docs/ROOFLINE.md``'s "measured" column
is filled from, and the first thing to run in a TPU window.

Legs (``PROF_LEGS`` comma-list, default all):
  kernel       — bare ``hist_pallas_wave`` full passes (triple-layout
                 oracle) vs the MXU roofline
  kernelpacked — bare packed-lane kernel pass (63 leaves, count folded —
                 the shipped layout; packed-vs-kernel is the
                 launches-per-tree win at equal per-pass cost)
  kernelfused  — packed kernel WITH in-kernel sibling subtraction (the
                 shipped fast path; fused-vs-kernelpacked measures the
                 saved XLA subtraction + HBM round-trip)
  kernelint16  — packed+fused kernel in QUANTIZED int16 mode (ISSUE 11:
                 stochastic-rounded integer g/h, exact hi/lo bf16
                 passes, int16 vector stream — vs the same-shape f32
                 legs the delta is the quantization economics)
  kernelint8   — same at int8 (one exact bf16 pass)
  fusedgrad    — gradient-stream microbench: (grad jit -> [N] g/h ->
                 grow jit) vs ONE jit computing gradients inline
                 (tpu_fused_grad), against ``grad_stream_bytes`` — the
                 per-iteration [N] round-trip the fused pass deletes
  full         — ``build_wave_grow_fn`` as shipped (packed + fused +
                 batched split apply)
  nofuse       — ``tpu_fused_sibling=false`` (the separate XLA
                 subtraction pass — full-vs-nofuse is the fusion win)
  triple       — packed=False, fused off (the PR-7-era grower, the
                 packed-channel differential oracle end to end)
  seqapply     — ``batched_apply=False`` (the per-split partition oracle)
  nokernel     — kernel stubbed to shaped noise (everything-but-kernel)
  nocompact    — ``compact=False`` (no tier gathers, full-N kernel/wave)
  gathers      — compaction-primitive microbenches (index build + tier
                 gathers, the nocompact-vs-full arbitration)
  partition    — wave-partition microbench: the batched one-pass split
                 apply AND the sequential per-split walk on the same slot
                 tables, each against ``splitter.partition_cost``

Env knobs: ``PROF_ROWS`` (1_000_000), ``PROF_FEATURES`` (28),
``PROF_LEAVES`` (255), ``PROF_MAXBIN`` (255), ``PROF_CAPACITY`` (63),
``PROF_REPEAT`` (3), ``PROF_LEGS``, ``PROF_JSON=1`` (append one
machine-readable JSON line), ``PROF_INTERPRET=1`` (Pallas interpreter
mode — the CPU smoke path CI exercises between TPU windows).
``PROF_TRACE_DIR=<dir>`` switches to trace-report mode: instead of
running legs, parse an existing ``jax.profiler`` capture through
``obs/xprof.py`` and print its measured-roofline table (the same
``kernel_measured`` rows training runs emit); ``PROF_TRACE_ITERS``
(1) tells the cost models how many iterations the window covered.

With a telemetry sink configured (``LGBM_TPU_TELEMETRY``) every timed leg
also emits a ``kernel_profile`` event, so ``tools/telemetry_report.py``
and ``bench_history.py`` see harness runs like training runs.

Run: PYTHONPATH=/root/repo:/root/.axon_site python tools/prof_kernels.py
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

import lightgbm_tpu as lgb  # noqa: E402
from lightgbm_tpu import obs  # noqa: E402
from lightgbm_tpu.core import wave_grower  # noqa: E402
from lightgbm_tpu.core.histogram import hist_onehot_cost  # noqa: E402
from lightgbm_tpu.core.meta import (SplitConfig,  # noqa: E402
                                    build_device_meta)
from lightgbm_tpu.core.splitter import split_scan_cost  # noqa: E402
from lightgbm_tpu.obs.profile import (cost_analysis_dict,  # noqa: E402
                                      device_peaks, extract_cost,
                                      roofline_seconds)
from lightgbm_tpu.ops import pallas_hist  # noqa: E402

INTERP = os.environ.get("PROF_INTERPRET", "") not in ("", "0")
MODE = "2xbf16"


def _env_int(name, default):
    return int(os.environ.get(name, default))


def timeit(fn, *args, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.time()
    out = None
    for _ in range(n):
        out = jax.block_until_ready(fn(*args))
    return (time.time() - t0) / n, out


def build_problem(rows: int, F: int, leaves: int, max_bin: int):
    """Synthetic HIGGS-shaped problem + device-resident inputs."""
    rng = np.random.default_rng(0)
    X = rng.normal(size=(rows, F))
    w = rng.normal(size=min(8, F))
    y = (X[:, :len(w)] @ w + 0.5 * X[:, 0] * X[:, 1]
         + rng.logistic(size=rows) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": leaves,
              "min_data_in_leaf": max(rows // 10_000, 5), "verbose": -1,
              "max_bin": max_bin}
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    cfg = lgb.Config.from_params(params)
    meta, B = build_device_meta(ds._handle, cfg)
    scfg = SplitConfig.from_config(cfg)
    binsT = jnp.asarray(np.ascontiguousarray(ds._handle.X_bin.T))
    g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    h = jnp.asarray((rng.random(rows) * 0.25).astype(np.float32))
    mask = jnp.ones(rows, jnp.float32)
    fmask = jnp.ones(F, bool)
    return dict(meta=meta, B=B, scfg=scfg, binsT=binsT, g=g, h=h,
                mask=mask, fmask=fmask, rows=rows, F=F,
                capacity=_env_int("PROF_CAPACITY", 63),
                block_rows=_env_int("PROF_BLOCK_ROWS", 1024))


def _report(results: dict, name: str, seconds: float, flops=None,
            nbytes=None, extra=None):
    """Record one measured leg: print, remember, and (sink permitting)
    emit the kernel_profile event through the shared profile machinery."""
    rec = {"seconds": round(seconds, 6)}
    line = f"{name:<26} {seconds * 1e3:9.2f} ms"
    if flops is not None:
        rf = roofline_seconds(flops, nbytes or 0.0)
        rec.update(flops=flops, bytes=nbytes,
                   roofline_s=round(rf, 9),
                   roofline_frac=round(rf / seconds, 6) if seconds else 0.0)
        line += (f"  roofline {rf * 1e3:9.3f} ms"
                 f"  frac {rec['roofline_frac']:8.4f}")
        obs.record_kernel(f"prof/{name}", flops, nbytes or 0.0, seconds,
                          source="prof_kernels")
    if extra:
        rec.update(extra)
    results[name] = rec
    print(line, flush=True)


def leg_kernel(p, results, n_rep: int, name="kernel full pass",
               packed=False, fused=False, mode=None):
    """Bare wave-kernel full passes vs the analytical MXU roofline AND
    XLA's own cost_analysis of the compiled kernel.  ``packed`` runs the
    lane-pair layout (63 leaves, count folded), ``fused`` additionally
    feeds a parent operand so the sibling subtraction happens in-kernel,
    ``mode`` overrides the precision mode (quantized legs pre-quantize
    g/h with ``stochastic_round`` exactly as the grower does) — the
    variants share one problem, so their deltas ARE the layout/precision
    economics."""
    rows, F, B = p["rows"], p["F"], p["B"]
    mode = mode or MODE
    rng = np.random.default_rng(1)
    lanes = 2 if packed else 3
    Pcap = max(1, min(p["capacity"], pallas_hist.wave_capacity_max(packed)))
    sl = np.full(pallas_hist.C_MAX, -1, np.int32)
    sl[:lanes * Pcap] = np.repeat(np.arange(Pcap), lanes)
    slot_leaf = jnp.asarray(sl)
    leaf_id = jnp.asarray(rng.integers(0, Pcap, rows, dtype=np.int32))
    g, h = p["g"], p["h"]
    if mode in pallas_hist.QUANT_MODES:
        qmax = pallas_hist.QUANT_QMAX[mode]
        s_g = jnp.maximum(jnp.max(jnp.abs(g)), 1e-30) / qmax
        s_h = jnp.maximum(jnp.max(jnp.abs(h)), 1e-30) / qmax
        g = pallas_hist.stochastic_round(g / s_g, 0)
        h = pallas_hist.stochastic_round(h / s_h, 0)
    parent = None
    if fused:
        shape = (F, B, pallas_hist.C_MAX)
        par = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        parent = (par, par) if packed else par
    # feat_block from the same VMEM model the grower uses — the fused
    # blocks at B=256 don't fit the default FB=32 on a real chip
    _, FBk = pallas_hist.select_wave_blocks(
        B, mode=mode, packed=packed, fused=fused,
        block_rows=p["block_rows"])
    kf = jax.jit(lambda: pallas_hist.hist_pallas_wave(
        p["binsT"], g, h, p["mask"], leaf_id, slot_leaf, B=B,
        block_rows=p["block_rows"], feat_block=FBk, highest=mode,
        interpret=INTERP, packed=packed, parent=parent))
    flops, nbytes = pallas_hist.wave_kernel_cost(rows, F, B, mode,
                                                 packed=packed, fused=fused)
    extra = {"leaves_per_launch": Pcap}
    try:
        ca = extract_cost(cost_analysis_dict(kf.lower().compile()))
        extra.update(xla_flops=ca[0], xla_bytes=ca[1])
    except Exception as exc:  # noqa: BLE001 — interpret mode may decline
        extra["xla_cost_error"] = f"{type(exc).__name__}"
    dt, _ = timeit(kf, n=n_rep)
    _report(results, name, dt, flops, nbytes, extra)


def leg_partition(p, results, n_rep: int):
    """Wave-partition leg: the batched one-pass split apply vs the
    sequential per-split walk, on identical synthetic slot tables, each
    against ``splitter.partition_cost`` — the measured arbitration of
    docs/ROOFLINE.md's sequential-vs-one-pass table.  Pure XLA (no
    Pallas), so it smokes on CPU regardless of PROF_INTERPRET."""
    from lightgbm_tpu.core.grower import go_left_node
    from lightgbm_tpu.core.splitter import bitset_words, partition_cost
    from lightgbm_tpu.core.wave_grower import (WaveSplits,
                                               build_split_apply_fn)
    rows, F, B = p["rows"], p["F"], p["B"]
    meta = p["meta"]
    Pcap = max(1, min(p["capacity"], pallas_hist.C_MAX // 3))
    L = 2 * Pcap + 2
    rng = np.random.default_rng(4)
    W = bitset_words(B)
    feats = rng.integers(0, F, Pcap).astype(np.int32)
    nb = np.asarray(meta.num_bins)
    ws = WaveSplits(
        ok=jnp.ones((Pcap,), bool),
        leaf=jnp.arange(Pcap, dtype=jnp.int32),
        new=jnp.arange(Pcap, 2 * Pcap, dtype=jnp.int32),
        feature=jnp.asarray(feats),
        threshold=jnp.asarray((nb[feats] // 2).astype(np.int32)),
        default_left=jnp.asarray(rng.random(Pcap) < 0.5),
        cat_bitset=jnp.zeros((Pcap, W), jnp.uint32))
    leaf_id0 = jnp.asarray(rng.integers(0, Pcap, rows, dtype=np.int32))
    bins_rm = jnp.asarray(np.asarray(p["binsT"]).T.copy())

    apply_fn = jax.jit(build_split_apply_fn(meta, L))
    dt, _ = timeit(apply_fn, leaf_id0, bins_rm, ws, n=n_rep)
    flops, nbytes = partition_cost(rows, splits=Pcap, batched=True, waves=1)
    _report(results, "partition one-pass", dt, flops, nbytes,
            {"splits": Pcap})

    binsT = p["binsT"]

    def seq(leaf_id):
        def body(i, lid):
            f = ws.feature[i]
            col = binsT[f].astype(jnp.int32)
            go = go_left_node(col, ws.threshold[i], ws.default_left[i],
                              meta.is_categorical[f], ws.cat_bitset[i],
                              meta.missing_types[f], meta.num_bins[f],
                              meta.default_bins[f])
            return jnp.where((lid == ws.leaf[i]) & ~go, ws.new[i], lid)
        return jax.lax.fori_loop(0, Pcap, body, leaf_id)

    dt2, _ = timeit(jax.jit(seq), leaf_id0, n=n_rep)
    flops2, nbytes2 = partition_cost(rows, splits=Pcap, batched=False)
    _report(results, "partition sequential", dt2, flops2, nbytes2,
            {"splits": Pcap,
             "speedup_one_pass": round(dt2 / dt, 2) if dt else None})


def leg_grow(p, results, name: str, n_rep: int, compact=True,
             stub_kernel=False, batched_apply=True, packed=True,
             fused=True):
    """One grower variant, timed end to end per tree."""
    rows, F, B = p["rows"], p["F"], p["B"]
    real = pallas_hist.hist_pallas_wave
    if stub_kernel:
        def stub(bins_fm, gv, hv, cv, leaf_id, slot_leaf, B, packed=False,
                 parent=None, **kw):
            """Shape-compatible fake histograms with enough structure that
            the grower keeps splitting (positive counts/hessians, wiggly g
            sums) — measures everything-but-kernel.  Speaks both channel
            layouts and the fused (child, sibling) contract."""
            Fdim = bins_fm.shape[0]
            i = jnp.arange(B, dtype=jnp.float32)[None, :, None]
            c = jnp.arange(pallas_hist.C_MAX, dtype=jnp.float32)[None, None, :]
            f = jnp.arange(Fdim, dtype=jnp.float32)[:, None, None]
            base = jnp.sin(i * 0.37 + c * 1.3 + f * 2.1)
            s = (gv[0] + hv[0] + cv[0] + leaf_id[0].astype(jnp.float32)) * 0
            if packed:
                kind = (jnp.arange(pallas_hist.C_MAX) % 2)[None, None, :]
                gh = jnp.where(kind == 0, base * 3.0, 40.0 + 0.0 * base) + s
                child = (gh, 160.0 + 0.0 * base + s)
            else:
                kind = (jnp.arange(pallas_hist.C_MAX) % 3)[None, None, :]
                child = jnp.where(
                    kind == 0, base * 3.0,
                    jnp.where(kind == 1, 40.0 + 0.0 * base,
                              160.0 + 0.0 * base)) + s
            if parent is None:
                return child
            if packed:
                sib = tuple(pa - ch for pa, ch in zip(parent, child))
            else:
                sib = parent - child
            return child, sib
        wave_grower.hist_pallas_wave = stub
    try:
        grow = jax.jit(wave_grower.build_wave_grow_fn(
            p["meta"], p["scfg"], B, wave_capacity=p["capacity"],
            highest=MODE, gain_gate=0.5, block_rows=p["block_rows"],
            compact=compact, interpret=INTERP, report_waves=True,
            batched_apply=batched_apply, packed=packed,
            fused_sibling=fused))
        t0 = time.time()
        tr, lid, stats = grow(p["binsT"], p["g"], p["h"], p["mask"],
                              p["fmask"])
        jax.block_until_ready(lid)
        compile_s = time.time() - t0
        dt, (tr, lid, stats) = timeit(grow, p["binsT"], p["g"], p["h"],
                                      p["mask"], p["fmask"], n=n_rep)
    finally:
        wave_grower.hist_pallas_wave = real
    waves, kern_rows = (int(x) for x in np.asarray(stats)[:2])
    leaves = int(tr.num_leaves)
    flops = nbytes = None
    if not stub_kernel:
        # kernel share of this tree, from the EXACT rows histogrammed
        flops, nbytes = pallas_hist.wave_kernel_cost(
            kern_rows, F, B, MODE, waves=waves, packed=packed, fused=fused)
    _report(results, name, dt, flops, nbytes,
            {"leaves": leaves, "waves": waves, "kernel_rows": kern_rows,
             "compile_s": round(compile_s, 1), "packed": packed,
             "fused_sibling": fused,
             "full_pass_equiv": round(kern_rows / rows, 2)})


def leg_fusedgrad(p, results, n_rep: int):
    """Gradient-stream microbench (ISSUE 11): the per-iteration
    [N]-sized legs ``tpu_fused_grad`` deletes.  "gradstream separate"
    computes a binary-logloss-shaped gradient in its OWN jit (g/h
    materialize as device arrays) and consumes them in a second jit —
    the unfused pipeline's structure; "gradstream fused" runs the SAME
    math inside one jit so XLA fuses the gradient chain into the
    consumer.  Both legs report against ``grad_stream_bytes``.  The
    consumer is the quantize+pack prologue (int16), the exact fusion
    partner the quantized wave path feeds.  Both legs pay the same
    score/label reads, which grad_stream_bytes deliberately leaves out
    — the modeled DELTA between the legs is the round-trip, and the
    delta is what the A/B arbitrates."""
    rows = p["rows"]
    rng = np.random.default_rng(3)
    score = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    label = jnp.asarray((rng.random(rows) < 0.5).astype(np.float32))
    qmax = pallas_hist.QUANT_QMAX["int16"]

    def grad(score):
        prob = 1.0 / (1.0 + jnp.exp(-score))
        return prob - label, prob * (1.0 - prob)

    # the REAL quantize+pack prologue shape: all four vector lanes
    # (g, h, count-weight, leaf) as [N, 4] int16 — so the measured
    # write stream is the same 8 B/row grad_stream_bytes charges
    leaf = jnp.zeros((rows,), jnp.float32)
    cv = jnp.ones((rows,), jnp.float32)

    def pack(g, h):
        s_g = jnp.maximum(jnp.max(jnp.abs(g)), 1e-30) / qmax
        s_h = jnp.maximum(jnp.max(jnp.abs(h)), 1e-30) / qmax
        gq = pallas_hist.stochastic_round(g / s_g, 0)
        hq = pallas_hist.stochastic_round(h / s_h, 0)
        return jnp.stack([gq, hq, cv, leaf], axis=1).astype(jnp.int16)

    grad_jit = jax.jit(grad)
    pack_jit = jax.jit(pack)

    def separate(score):
        g, h = grad_jit(score)          # [N] f32 g/h materialize
        return pack_jit(g, h)           # ...and are read back

    fused_jit = jax.jit(lambda s: pack(*grad(s)))
    nb_sep = pallas_hist.grad_stream_bytes(rows, 0.0, "int16",
                                           fused_grad=False)
    nb_fus = pallas_hist.grad_stream_bytes(rows, 0.0, "int16",
                                           fused_grad=True)
    dt, _ = timeit(separate, score, n=n_rep)
    _report(results, "gradstream separate", dt, 8.0 * rows, nb_sep)
    dt2, _ = timeit(fused_jit, score, n=n_rep)
    _report(results, "gradstream fused", dt2, 8.0 * rows, nb_fus,
            {"speedup_fused": round(dt / dt2, 2) if dt2 else None})


def leg_gathers(p, results, n_rep: int):
    """Compaction-primitive microbenches: the nocompact-vs-full
    arbitration (are tier gathers cheaper than the kernel rows saved?)."""
    rows = p["rows"]
    rng = np.random.default_rng(2)
    active = jnp.asarray(rng.random(rows) < 0.3)
    T = max(rows // 2, 1)
    binsT = p["binsT"]
    bins_rm = jnp.asarray(np.asarray(binsT).T.copy())

    def idx_build():
        pos = jnp.cumsum(active.astype(jnp.int32))
        return jnp.zeros((rows,), jnp.int32).at[
            jnp.where(active, pos - 1, rows)
        ].set(jnp.arange(rows, dtype=jnp.int32), mode="drop")

    dt, idx = timeit(jax.jit(idx_build), n=n_rep)
    _report(results, "index build", dt)
    idx_t = idx[:T]
    dt, _ = timeit(jax.jit(
        lambda i: jnp.transpose(jnp.take(bins_rm, i, axis=0))), idx_t,
        n=n_rep)
    _report(results, f"tier gather T={T}", dt)
    g3 = jax.jit(lambda i: jnp.stack([p["g"], p["h"], p["mask"]], 1)[i])
    dt, _ = timeit(g3, idx_t, n=n_rep)
    _report(results, "vec3 gather", dt)


def report_trace(trace_dir: str, rows: int, F: int, leaves: int,
                 max_bin: int) -> int:
    """Measured-roofline table from an existing profiler capture.

    ``PROF_TRACE_DIR=<dir>`` replaces the microbench legs with the
    obs/xprof.py pipeline over a trace some training run (or
    tpu_window leg) already captured: parse, attribute per ``lgbm/*``
    scope, join against the cost models under the PROF_* problem shape
    — the exact ``kernel_measured`` rows the digest/report render, so
    the harness and the training plane arbitrate from ONE table."""
    from lightgbm_tpu.obs import xprof
    parsed = xprof.parse_trace_dir(trace_dir)
    if parsed["files"] == 0:
        print(f"no trace artifacts under {trace_dir}", flush=True)
        return 1
    attrib = xprof.attribute(parsed)
    context = {"rows": rows, "features": F, "bins": max_bin,
               "leaves": leaves, "mode": MODE,
               "iters": _env_int("PROF_TRACE_ITERS", 1)}
    rows_out = xprof.measured_rooflines(attrib, context)
    if parsed["errors"]:
        print("parse errors: " + "; ".join(parsed["errors"]), flush=True)
    print(f"trace: {parsed['parsed']}/{parsed['files']} artifact(s), "
          f"window {attrib['window_ms']:.1f} ms", flush=True)
    print(f"{'kernel':<30}{'ops':>7}{'measured':>11}{'model':>11}"
          f"{'frac':>8}{'bound':>7}", flush=True)
    for r in sorted(rows_out, key=lambda r: -r["measured_ms"]):
        model = (f"{r['model_ms']:>9.3f}ms" if r.get("model_ms") is not None
                 else f"{'—':>11}")
        frac = (f"{r['roofline_frac']:>8.4f}"
                if r.get("roofline_frac") is not None else f"{'—':>8}")
        print(f"{r['kernel']:<30}{r['ops']:>7}{r['measured_ms']:>9.3f}ms"
              f"{model}{frac}{r.get('bound', '—'):>7}", flush=True)
    if os.environ.get("PROF_JSON", "") not in ("", "0"):
        print(json.dumps({
            "tool": "prof_kernels", "source": "xprof",
            "trace_dir": trace_dir, "window_ms": attrib["window_ms"],
            "parse_errors": parsed["errors"],
            "kernel_measured": rows_out}))
    return 0


def main() -> int:
    rows = _env_int("PROF_ROWS", 1_000_000)
    F = _env_int("PROF_FEATURES", 28)
    leaves = _env_int("PROF_LEAVES", 255)
    max_bin = _env_int("PROF_MAXBIN", 255)
    n_rep = _env_int("PROF_REPEAT", 3)
    trace_dir = os.environ.get("PROF_TRACE_DIR", "")
    if trace_dir:
        return report_trace(trace_dir, rows, F, leaves, max_bin)
    legs = [s for s in os.environ.get(
        "PROF_LEGS",
        "kernel,kernelpacked,kernelfused,kernelint16,kernelint8,fusedgrad,"
        "full,nofuse,triple,seqapply,nokernel,nocompact,gathers,partition"
    ).split(",") if s]
    pf, pb = device_peaks()
    print(f"backend: {jax.default_backend()}  interpret: {INTERP}  "
          f"peaks: {pf / 1e12:.1f} TFLOP/s, {pb / 1e9:.0f} GB/s",
          flush=True)
    p = build_problem(rows, F, leaves, max_bin)
    results = {}
    if "kernel" in legs:
        leg_kernel(p, results, n_rep)
    if "kernelpacked" in legs:
        leg_kernel(p, results, n_rep, name="kernel packed", packed=True)
    if "kernelfused" in legs:
        leg_kernel(p, results, n_rep, name="kernel packed+fused",
                   packed=True, fused=True)
    if "kernelint16" in legs:
        leg_kernel(p, results, n_rep, name="kernel int16",
                   packed=True, fused=True, mode="int16")
    if "kernelint8" in legs:
        leg_kernel(p, results, n_rep, name="kernel int8",
                   packed=True, fused=True, mode="int8")
    if "fusedgrad" in legs:
        leg_fusedgrad(p, results, n_rep)
    if "full" in legs:
        leg_grow(p, results, "grow full", n_rep)
    if "nofuse" in legs:
        leg_grow(p, results, "grow nofuse", n_rep, fused=False)
    if "triple" in legs:
        leg_grow(p, results, "grow triple", n_rep, packed=False,
                 fused=False)
    if "seqapply" in legs:
        leg_grow(p, results, "grow seqapply", n_rep, batched_apply=False)
    if "nokernel" in legs:
        leg_grow(p, results, "grow nokernel", n_rep, stub_kernel=True)
    if "nocompact" in legs:
        leg_grow(p, results, "grow nocompact", n_rep, compact=False)
    if "gathers" in legs:
        leg_gathers(p, results, n_rep)
    if "partition" in legs:
        leg_partition(p, results, n_rep)

    # the split-scan hypothesis (ROOFLINE.md step 3): expected non-kernel
    # floor from the analytical scan cost alone
    sf, sb = split_scan_cost(F, p["B"], leaves=2 * p["capacity"])
    print(f"split-scan model (per wave, 2P leaves): "
          f"{roofline_seconds(sf, sb) * 1e3:.3f} ms", flush=True)
    oh = hist_onehot_cost(rows, F, p["B"])
    print(f"XLA one-hot fallback roofline (same pass): "
          f"{roofline_seconds(*oh) * 1e3:.3f} ms", flush=True)

    if os.environ.get("PROF_JSON", "") not in ("", "0"):
        print(json.dumps({
            "tool": "prof_kernels", "backend": jax.default_backend(),
            "interpret": INTERP, "rows": rows, "features": F,
            "leaves": leaves, "max_bin": max_bin, "mode": MODE,
            "peak_flops": pf, "peak_bw": pb, "legs": results}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
