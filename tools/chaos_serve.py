"""Serving chaos matrix: prove every fleet failure mode's steady state.

The serving twin of ``tools/fault_matrix.py`` (which proves the
TRAINING recovery branches): each scenario injects a deterministic
fault into the serving fleet (robust/faults.py points
``serve_replica_{i}`` / ``serve_canary`` / ``serve_device``) or drives
an overload, and asserts the documented steady state — on CPU, in one
process, every suite round (``tools/run_suite.py`` runs this as the
``chaos`` tier):

- **replica_wedge** — one replica of a 2-replica router wedges; every
  request still succeeds on the survivor (capacity degrades, not
  availability), the breaker opens, and after the fault clears the
  half-open probe closes it again.
- **swap_mid_flight** — a canary-gated hot swap lands under concurrent
  mixed /predict + /explain HTTP traffic: zero request loss, no 5xx
  from the swap itself, every response attributable to exactly one
  model version (version echoed and predictions bit-match that
  version's model), ``swap_blip_p99_ms`` recorded vs the steady p99.
- **canary_fail** — an injected canary fault rejects the push with 409;
  the old version never stops serving.
- **rollback_trigger** — a post-swap device wedge degrades the new
  version; ``check_postswap`` trips the degraded-transition threshold,
  rolls back to the still-resident previous version, dumps the flight
  recorder, and traffic keeps succeeding on the restored version.
- **shed_priority** — a saturated queue sheds LOW-priority requests
  while HIGH is still admitted; the per-class shed/served counters land
  in /metrics and the 503 carries ``Retry-After``.
- **drift** — seeded covariate-shifted traffic drives feature PSI past
  ``tpu_drift_psi_warn`` within one cadence check (breach latched,
  flight recorder dumped), while a clean replay of training-distribution
  rows stays below the threshold — detection AND false-alarm sides of
  the drift plane (obs/drift.py).

    python tools/chaos_serve.py --json     # one JSON verdict line
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _p99(lat):
    from lightgbm_tpu.obs.report import percentile
    return percentile(sorted(lat), 0.99)


def _build_models(workdir):
    """Two small models whose predictions DIFFER (so a response is
    attributable to exactly one of them) + the probe pool."""
    import lightgbm_tpu as lgb
    rng = np.random.default_rng(5)
    X = rng.normal(size=(600, 6))
    X[rng.random(X.shape) < 0.03] = np.nan
    y = (np.nan_to_num(X[:, 0]) - 0.4 * np.nan_to_num(X[:, 2]) > 0
         ).astype(np.float64)
    P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1}
    b1 = lgb.train(P, lgb.Dataset(X, label=y, params=P),
                   num_boost_round=5)
    P2 = dict(P, num_leaves=5, learning_rate=0.2)
    b2 = lgb.train(P2, lgb.Dataset(X, label=y, params=P2),
                   num_boost_round=8)
    m1 = os.path.join(workdir, "chaos_m1.txt")
    m2 = os.path.join(workdir, "chaos_m2.txt")
    b1.save_model(m1)
    b2.save_model(m2)
    return (m1, b1), (m2, b2), X, dict(P)


def _cfg(P, **over):
    from lightgbm_tpu.config import Config
    base = dict(P, tpu_serve_max_batch=64, tpu_serve_max_wait_ms=1.0,
                tpu_serve_canary_rows=16, tpu_serve_canary_probes=4,
                tpu_serve_rollback_watch_s=0.0,  # chaos drives the check
                tpu_serve_reprobe_s=0.05)
    base.update(over)
    return Config.from_params(base)


# ---------------------------------------------------------------------------
def scenario_replica_wedge(models, X, P):
    """One wedged replica degrades capacity, not availability."""
    from lightgbm_tpu.robust import faults
    from lightgbm_tpu.serve import ReplicaRouter
    (m1, b1) = models[0]
    router = ReplicaRouter(m1, n_replicas=2, config=_cfg(P))
    ref = b1.predict(X[:8])
    try:
        faults.configure("serve_replica_0:raise@n=-1")
        outs, fails = [], 0
        for i in range(12):
            try:
                t = router.submit(X[:8])
                outs.append((t.replica.idx, router.result(t, timeout=30)))
            except Exception:  # noqa: BLE001
                fails += 1
        check("wedge.all_served", fails == 0 and len(outs) == 12,
              f"{fails} failures")
        check("wedge.correct_on_survivor",
              all(np.allclose(o, ref, atol=1e-6) for _, o in outs))
        st = router.stats()
        r0 = st["replicas"][0]["breaker"]
        check("wedge.breaker_opened", r0["state"] == "open"
              and st["failovers"] >= 1, f"breaker {r0}")
        check("wedge.capacity_degraded",
              st["routable_replicas"] == 1
              and not st["degraded"], st)
        # fault clears -> the half-open probe re-admits replica 0
        faults.disarm()
        deadline = time.time() + 10
        closed = False
        while time.time() < deadline:
            t = router.submit(X[:4])
            router.result(t, timeout=30)
            if router.replicas[0].breaker.state == "closed":
                closed = True
                break
            time.sleep(0.2)
        check("wedge.recovered_after_clear", closed
              and router.routable_count() == 2,
              router.replicas[0].breaker.snapshot())
    finally:
        faults.disarm()
        router.close()


# ---------------------------------------------------------------------------
def scenario_swap_mid_flight(models, X, P):
    """Hot swap under concurrent mixed HTTP traffic: zero loss, version
    attribution, no 5xx from the swap, blip p99 recorded."""
    from lightgbm_tpu.serve import ModelRegistry, PredictServer
    (m1, b1), (m2, b2) = models
    expected = {}   # version -> (predict ref, contrib ref)
    reg = ModelRegistry(config=_cfg(P), n_replicas=1)
    reg.add_model("default", m1)
    server = PredictServer(reg).start()
    url = server.url
    results, lock = [], threading.Lock()
    stop = threading.Event()
    pool = X[:32]
    expected[1] = (b1.predict(pool), b1.predict(pool, pred_contrib=True))
    expected[2] = (b2.predict(pool), b2.predict(pool, pred_contrib=True))

    def client(seed):
        rng = np.random.default_rng(seed)
        while not stop.is_set():
            n = int(rng.integers(1, 9))
            lo = int(rng.integers(0, pool.shape[0] - n + 1))
            explain = rng.random() < 0.3
            path = "/explain" if explain else "/predict"
            t0 = time.perf_counter()
            try:
                code, body, _ = _post(url + path,
                                      {"rows": pool[lo:lo + n].tolist()})
            except urllib.error.HTTPError as exc:
                code, body = exc.code, {}
            except Exception as exc:  # noqa: BLE001
                code, body = -1, {"error": repr(exc)}
            with lock:
                results.append({
                    "t0": t0, "t": time.perf_counter(), "code": code,
                    "lat_ms": (time.perf_counter() - t0) * 1e3,
                    "version": body.get("version"), "lo": lo, "n": n,
                    "explain": explain,
                    "values": body.get("contributions"
                                       if explain else "predictions")})
            time.sleep(0.01)

    threads = [threading.Thread(target=client, args=(s,))
               for s in range(4)]
    for t in threads:
        t.start()
    time.sleep(1.2)
    t_swap0 = time.perf_counter()
    code, swap_body, _ = _post(url + "/models/default/swap",
                               {"model_file": m2}, timeout=120)
    t_swap1 = time.perf_counter()
    time.sleep(1.2)
    stop.set()
    for t in threads:
        t.join(30)
    server.stop(close_session=True)

    ok_rows = [r for r in results if r["code"] == 200]
    check("swap.http_ok", code == 200 and swap_body.get("ok"), swap_body)
    check("swap.zero_loss", len(ok_rows) == len(results)
          and len(results) >= 8,
          f"{len(results) - len(ok_rows)}/{len(results)} non-200")
    vers = {r["version"] for r in ok_rows}
    check("swap.both_versions_observed", vers == {1, 2}, vers)
    mismatch = 0
    for r in ok_rows:
        pref, cref = expected[r["version"]]
        got = np.asarray(r["values"], dtype=np.float64)
        want = (cref[r["lo"]:r["lo"] + r["n"]] if r["explain"]
                else pref[r["lo"]:r["lo"] + r["n"]])
        if got.shape != np.asarray(want).shape or \
                not np.allclose(got, want, atol=1e-5):
            mismatch += 1
    check("swap.bit_consistent", mismatch == 0,
          f"{mismatch} responses did not match their echoed version")
    # ordering: a request STARTED after the swap call returned (flip
    # complete) must resolve the new version — old-version answers after
    # the flip can only be in-flight stragglers submitted before it
    after = [r for r in ok_rows if r["t0"] > t_swap1 + 0.05]
    check("swap.new_traffic_on_new_version",
          all(r["version"] == 2 for r in after) and len(after) > 0,
          {r["version"] for r in after})
    steady = [r["lat_ms"] for r in ok_rows
              if r["t"] < t_swap0 or r["t"] > t_swap1 + 0.5]
    blip = [r["lat_ms"] for r in ok_rows
            if t_swap0 <= r["t"] <= t_swap1 + 0.5]
    steady_p99, blip_p99 = _p99(steady), _p99(blip)
    check("swap.blip_recorded", steady_p99 is not None)
    return {"swap_blip_p99_ms": blip_p99, "steady_p99_ms": steady_p99,
            "swap_ms": round((t_swap1 - t_swap0) * 1e3, 1),
            "requests": len(results)}


# ---------------------------------------------------------------------------
def scenario_canary_fail(models, X, P):
    """An injected canary fault rejects the push; old model keeps
    serving."""
    from lightgbm_tpu.robust import faults
    from lightgbm_tpu.serve import ModelRegistry, PredictServer
    (m1, b1), (m2, _) = models
    reg = ModelRegistry(config=_cfg(P), n_replicas=1)
    reg.add_model("default", m1)
    server = PredictServer(reg).start()
    try:
        faults.configure("serve_canary:raise@call=1")
        try:
            code, body, _ = _post(server.url + "/models/default/swap",
                                  {"model_file": m2}, timeout=120)
        except urllib.error.HTTPError as exc:
            code, body = exc.code, json.loads(exc.read())
        faults.disarm()
        check("canary.rejected_409", code == 409
              and body.get("error") == "swap_rejected", (code, body))
        listing = reg.models()[0]
        check("canary.old_still_live", listing["live_version"] == 1
              and listing["swaps_rejected"] == 1, listing)
        code, body, _ = _post(server.url + "/predict",
                              {"rows": X[:4].tolist()})
        check("canary.serving_after_reject", code == 200
              and body.get("version") == 1
              and np.allclose(body["predictions"],
                              b1.predict(X[:4]), atol=1e-6))
    finally:
        faults.disarm()
        server.stop(close_session=True)


# ---------------------------------------------------------------------------
def scenario_rollback_trigger(models, X, P, art_dir):
    """Post-swap device wedge -> health regression -> automatic
    rollback to the resident previous version + flight dump."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.robust import faults
    from lightgbm_tpu.serve import ModelRegistry
    (m1, b1), (m2, _) = models
    # one degraded transition must trip the watch (the fleet shares one
    # metrics instance, so N replicas degrading counts ONE transition)
    reg = ModelRegistry(config=_cfg(P, tpu_serve_rollback_degraded=1),
                        n_replicas=1)
    reg.add_model("default", m1)
    try:
        swap = reg.swap("default", m2)
        check("rollback.swap_ok", swap["ok"], swap)
        n_flights0 = len(glob.glob(os.path.join(art_dir, "FLIGHT_*.json")))
        faults.configure("serve_device:raise@n=-1")
        outs = []
        for _ in range(4):   # device wedge -> host fallback, not errors
            t = reg.submit(X[:4])
            outs.append(reg.result(t, timeout=30))
        st = reg.resolve(None).router.stats()
        check("rollback.new_version_degraded", st["any_degraded"], st)
        out = reg.check_postswap("default")
        check("rollback.triggered", out is not None
              and str(out.get("reason", "")).startswith("auto:"), out)
        faults.disarm()
        live = reg.resolve(None)
        check("rollback.live_is_previous", live.version == 1,
              live.version)
        listing = reg.models()[0]
        check("rollback.counted", listing["rollbacks"] == 1, listing)
        n_flights1 = len(glob.glob(os.path.join(art_dir, "FLIGHT_*.json")))
        check("rollback.flight_dumped",
              obs.flight_enabled() and n_flights1 > n_flights0,
              f"{n_flights0} -> {n_flights1} in {art_dir}")
        t = reg.submit(X[:4])
        check("rollback.serving_after_rollback",
              np.allclose(reg.result(t, timeout=30), b1.predict(X[:4]),
                          atol=1e-6))
    finally:
        faults.disarm()
        reg.close()


# ---------------------------------------------------------------------------
def scenario_replica_restart(models, X, P):
    """Replica killed and cold-booted MID-STORM with the AOT store
    armed: zero request loss (the survivor absorbs, the old batcher
    drains on close), and the rebooted replica boots straight into the
    persisted executables — its first request pays no JIT compile."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.serve import ReplicaRouter
    (m1, b1) = models[0]
    with tempfile.TemporaryDirectory(prefix="chaos_aot_") as aot_dir:
        cfg = _cfg(P, tpu_serve_aot_dir=aot_dir)
        router = ReplicaRouter(m1, n_replicas=2, config=cfg)
        ref = b1.predict(X[:8])
        try:
            # warm every pow2 bucket; with the store armed this also
            # persists the executables the reboot will load
            router.warmup()
            aot_st = (router.stats() or {}).get("aot") or {}
            check("restart.store_armed", aot_st.get("entries", 0) >= 1,
                  aot_st)
            stop = threading.Event()
            served, failures, lock = [], [], threading.Lock()

            def client(seed):
                rng = np.random.default_rng(seed)
                while not stop.is_set():
                    n = int(rng.integers(1, 9))
                    try:
                        t = router.submit(X[:n])
                        out = router.result(t, timeout=60)
                        with lock:
                            served.append((n, out))
                    except Exception as exc:  # noqa: BLE001 — loss counter
                        with lock:
                            failures.append(repr(exc))
                    time.sleep(0.005)

            threads = [threading.Thread(target=client, args=(s,))
                       for s in range(3)]
            for t in threads:
                t.start()
            time.sleep(0.5)
            boot = router.restart_replica(0)
            time.sleep(0.5)
            stop.set()
            for t in threads:
                t.join(30)
            check("restart.zero_loss", not failures and len(served) >= 8,
                  f"{len(failures)} failures / {len(served)} served: "
                  f"{failures[:3]}")
            check("restart.correct_answers",
                  all(np.allclose(o, ref[:n], atol=1e-6)
                      for n, o in served))
            check("restart.boot_from_store",
                  boot["boot_compiles"] == 0 and boot["aot"], boot)
            # the rebooted replica's FIRST request: with the storm
            # stopped, a predict on its session must ride the loaded
            # executables — the process-global compile counter stays put
            c0 = obs.compile_count()
            first = router.replicas[0].session.predict(X[:5])
            check("restart.first_request_no_compile",
                  obs.compile_count() - c0 == 0
                  and np.allclose(first, ref[:5], atol=1e-6),
                  f"{obs.compile_count() - c0} compiles on request #1")
            return {"restart_boot_ms": boot["boot_ms"],
                    "restart_requests": len(served)}
        finally:
            router.close()


# ---------------------------------------------------------------------------
def scenario_shed_priority(models, X, P):
    """Saturated queue sheds low first; high still admitted; counters in
    /metrics; 503 carries Retry-After."""
    from lightgbm_tpu.serve import (PredictorSession, PredictServer,
                                    ServeOverloadError, parse_prometheus)
    (m1, _), _ = models
    cfg = _cfg(P, tpu_serve_max_batch=16, tpu_serve_queue_depth=64,
               tpu_serve_max_wait_ms=50.0)
    sess = PredictorSession(m1, config=cfg)
    sess.warmup()
    orig = sess._run_device

    def slow(bins, **kw):
        time.sleep(0.12)
        return orig(bins, **kw)

    sess._run_device = slow
    server = PredictServer(sess).start()
    tickets = []
    try:
        # low cap = 32 rows, normal cap = 54, high cap = 64.  Fill with
        # 48 normal rows (queue ~48 after the first batch dispatches)…
        shed_low = admitted_high = False
        for _ in range(6):
            tickets.append(sess.submit(X[:8], priority="normal"))
        # …low is over ITS budget now, high still has headroom
        try:
            sess.submit(X[:8], priority="low")
        except ServeOverloadError as exc:
            shed_low = exc.shed and exc.priority == "low"
        try:
            tickets.append(sess.submit(X[:8], priority="high"))
            admitted_high = True
        except ServeOverloadError:
            pass
        check("shed.low_shed_first", shed_low)
        check("shed.high_admitted", admitted_high)
        # the 503 a shed client sees carries Retry-After
        code, headers = None, {}
        try:
            code, _, headers = _post(
                server.url + "/predict",
                {"rows": X[:8].tolist(), "priority": "low"}, timeout=30)
        except urllib.error.HTTPError as exc:
            code, headers = exc.code, dict(exc.headers)
        check("shed.retry_after_on_503", code == 503
              and "Retry-After" in headers, (code, list(headers)))
        for t in tickets:
            sess.result(t, timeout=60)
        pm = parse_prometheus(
            urllib.request.urlopen(server.url + "/metrics", timeout=30)
            .read().decode())
        check("shed.counters_in_metrics",
              pm.get('tpu_serve_shed_total{priority="low"}', 0) >= 2
              and pm.get('tpu_serve_served_total{priority="high"}', 0)
              >= 1
              and pm.get('tpu_serve_shed_total{priority="high"}', 0)
              == 0,
              {k: v for k, v in pm.items() if "shed" in k or "served" in
               k})
    finally:
        server.stop(close_session=True)


# ---------------------------------------------------------------------------
def scenario_drift(models, X, P, art_dir):
    """Seeded covariate shift breaches the drift monitor (flight dump
    fired, breach latched); clean traffic stays quiet — the
    false-alarm side of the differential matters as much as the
    detection side."""
    from lightgbm_tpu.serve import ModelRegistry
    (m1, _), _ = models
    rng = np.random.default_rng(7)
    # pin the plane's knobs: every serve batch sampled, a small row
    # floor so forced checks score, cadence driven by force=True
    os.environ["LGBM_TPU_DRIFT_SAMPLE_RATE"] = "1.0"
    os.environ["LGBM_TPU_DRIFT_MIN_ROWS"] = "64"
    reg = ModelRegistry(config=_cfg(P), n_replicas=1)
    try:
        reg.add_model("default", m1)
        mon = getattr(reg.resolve(None).router, "drift", None)
        check("drift.monitor_armed", mon is not None,
              "no .quality.json sidecar beside the chaos model?")
        if mon is None:
            return
        # clean replay: the full training matrix in slices — a biased
        # subsample (e.g. the first 128 rows over and over) would shift
        # the PREDICTION histogram and fail the false-alarm side
        for s in range(0, len(X), 120):
            t = reg.submit(X[s:s + 120])
            reg.result(t, timeout=30)
        quiet = mon.maybe_check(force=True)
        check("drift.clean_quiet", quiet is not None
              and quiet["psi_max"] <= mon.psi_warn
              and mon.breach is None,
              quiet and {k: quiet[k] for k in ("psi_max", "pred_psi")})
        n0 = len(glob.glob(os.path.join(art_dir, "FLIGHT_*.json")))
        # covariate shift: scaled + offset marginals, same row shape
        for _ in range(4):
            t = reg.submit(rng.normal(size=(128, 6)) * 2.5 + 1.5)
            reg.result(t, timeout=30)
        flagged = mon.maybe_check(force=True)
        check("drift.shifted_flagged", flagged is not None
              and flagged["psi_max"] > mon.psi_warn
              and mon.breach is not None,
              flagged and {k: flagged[k] for k in ("psi_max",
                                                   "pred_psi")})
        n1 = len(glob.glob(os.path.join(art_dir, "FLIGHT_*.json")))
        check("drift.breach_flight_dump", n1 > n0,
              f"{n0} -> {n1} in {art_dir}")
    finally:
        os.environ.pop("LGBM_TPU_DRIFT_SAMPLE_RATE", None)
        os.environ.pop("LGBM_TPU_DRIFT_MIN_ROWS", None)
        reg.close()


# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Serving chaos matrix")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    args = ap.parse_args(argv)

    t0 = time.time()
    art = tempfile.mkdtemp(prefix="chaos_serve_")
    os.environ["LGBM_TPU_FLIGHT_DIR"] = art

    with tempfile.TemporaryDirectory(prefix="chaos_models_") as workdir:
        models = _build_models(workdir)
        (pair1, pair2, X, P) = models
        models = (pair1, pair2)
        extra = {}
        scenario_replica_wedge(models, X, P)
        extra.update(scenario_swap_mid_flight(models, X, P) or {})
        scenario_canary_fail(models, X, P)
        scenario_rollback_trigger(models, X, P, art)
        extra.update(scenario_replica_restart(models, X, P) or {})
        scenario_shed_priority(models, X, P)
        scenario_drift(models, X, P, art)

    record = {
        "kind": "chaos_serve",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "artifacts_dir": art,
        **extra,
    }
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
