"""Decompose wave-grower tree time on the real TPU (throwaway scratch).

Differences out the three cost hypotheses:
  full        — build_wave_grow_fn as shipped
  nokernel    — hist_pallas_wave stubbed to zeros (everything-but-kernel)
  nocompact   — compact=False (no tier gathers, full-N kernel every wave)
  kernel-only — bare hist_pallas_wave loop, 10 full passes
Run: PYTHONPATH=/root/repo:/root/.axon_site python prof_decompose.py
"""
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.core.meta import SplitConfig, build_device_meta
from lightgbm_tpu.ops import pallas_hist
from lightgbm_tpu.core import wave_grower

ROWS = int(os.environ.get("PROF_ROWS", 1_000_000))
# PROF_INTERPRET=1: run the Pallas kernel in interpreter mode so the
# script itself can be smoke-tested on CPU between TPU windows
INTERP = os.environ.get("PROF_INTERPRET", "") not in ("", "0")


def timeit(fn, *args, n=3, warmup=1):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.time()
    for _ in range(n):
        out = jax.block_until_ready(fn(*args))
    return (time.time() - t0) / n, out


def main():
    print("backend:", jax.default_backend(), flush=True)
    rng = np.random.default_rng(0)
    F = 28
    X = rng.normal(size=(ROWS, F))
    w = rng.normal(size=8)
    y = (X[:, :8] @ w + 0.5 * X[:, 0] * X[:, 1]
         + rng.logistic(size=ROWS) > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 255,
              "min_data_in_leaf": 100, "verbose": -1, "max_bin": 255}
    ds = lgb.Dataset(X, label=y, params=params)
    ds.construct()
    cfg = lgb.Config.from_params(params)
    meta, B = build_device_meta(ds._handle, cfg)
    scfg = SplitConfig.from_config(cfg)
    binsT = jnp.asarray(np.ascontiguousarray(ds._handle.X_bin.T))
    g = jnp.asarray(rng.normal(size=ROWS).astype(np.float32))
    h = jnp.asarray((rng.random(ROWS) * 0.25).astype(np.float32))
    mask = jnp.ones(ROWS, jnp.float32)
    fmask = jnp.ones(F, bool)

    # kernel-only: one full pass
    sl = np.full(pallas_hist.C_MAX, -1, np.int32)
    sl[:126] = np.repeat(np.arange(42), 3)
    slot_leaf = jnp.asarray(sl)
    leaf_id = jnp.asarray(rng.integers(0, 42, ROWS, dtype=np.int32))
    kf = jax.jit(lambda: pallas_hist.hist_pallas_wave(
        binsT, g, h, mask, leaf_id, slot_leaf, B=B, block_rows=1024,
        highest="2xbf16", interpret=INTERP))
    dt, _ = timeit(kf, n=10)
    print(f"kernel full pass:    {dt*1e3:8.1f} ms", flush=True)

    variants = {}
    grow_full = jax.jit(wave_grower.build_wave_grow_fn(
        meta, scfg, B, wave_capacity=42, highest="2xbf16", gain_gate=0.5,
        interpret=INTERP))
    variants["full"] = grow_full
    grow_nc = jax.jit(wave_grower.build_wave_grow_fn(
        meta, scfg, B, wave_capacity=42, highest="2xbf16", gain_gate=0.5,
        compact=False, interpret=INTERP))
    variants["nocompact"] = grow_nc

    # stub the kernel: same signature/shape, no MXU work
    real = pallas_hist.hist_pallas_wave

    def stub(bins_fm, gv, hv, cv, leaf_id, slot_leaf, B, **kw):
        """Shape-compatible fake histograms with enough structure that the
        grower keeps splitting (positive counts/hessians, wiggly g sums) —
        measures everything-but-kernel; check the reported leaf count."""
        Fdim = bins_fm.shape[0]
        i = jnp.arange(B, dtype=jnp.float32)[None, :, None]
        c = jnp.arange(pallas_hist.C_MAX, dtype=jnp.float32)[None, None, :]
        f = jnp.arange(Fdim, dtype=jnp.float32)[:, None, None]
        base = jnp.sin(i * 0.37 + c * 1.3 + f * 2.1)
        kind = (jnp.arange(pallas_hist.C_MAX) % 3)[None, None, :]
        out = jnp.where(kind == 0, base * 3.0,
                        jnp.where(kind == 1, 40.0 + 0.0 * base,
                                  160.0 + 0.0 * base))
        # trivial data dependence so nothing is DCE'd
        s = (gv[0] + hv[0] + cv[0] + leaf_id[0].astype(jnp.float32)) * 0
        return out + s

    wave_grower.hist_pallas_wave = stub
    grow_nk = jax.jit(wave_grower.build_wave_grow_fn(
        meta, scfg, B, wave_capacity=42, highest="2xbf16", gain_gate=0.5,
        interpret=INTERP))
    # trace/compile NOW, while the stub is installed — the closure looks
    # hist_pallas_wave up late-bound at trace time
    jax.block_until_ready(grow_nk(binsT, g, h, mask, fmask)[1])
    variants["nokernel"] = grow_nk
    wave_grower.hist_pallas_wave = real

    for name, fn in variants.items():
        t0 = time.time()
        tr, lid = fn(binsT, g, h, mask, fmask)
        jax.block_until_ready(lid)
        ct = time.time() - t0
        dt, (tr, lid) = timeit(fn, binsT, g, h, mask, fmask, n=3)
        print(f"grow {name:10s}: {dt*1e3:8.1f} ms  (compile {ct:.0f}s, "
              f"leaves={int(tr.num_leaves)})", flush=True)

    # ---- compaction-primitive microbenches -----------------------------
    # hypothesis: the tier gathers + index scatter dominate non-kernel time
    active = jnp.asarray(rng.random(ROWS) < 0.3)
    T = ROWS // 2
    bins_rm = jnp.asarray(np.asarray(binsT).T.copy())  # row-major [N, F]

    def idx_build():
        pos = jnp.cumsum(active.astype(jnp.int32))
        return jnp.zeros((ROWS,), jnp.int32).at[
            jnp.where(active, pos - 1, ROWS)
        ].set(jnp.arange(ROWS, dtype=jnp.int32), mode="drop")

    jidx = jax.jit(idx_build)
    dt, idx = timeit(jidx, n=10)
    print(f"index build (cumsum+scatter): {dt*1e3:8.2f} ms", flush=True)
    idx_t = idx[:T]

    g_fm = jax.jit(lambda i: jnp.take(binsT, i, axis=1))
    dt, _ = timeit(g_fm, idx_t, n=10)
    print(f"gather feature-major [F,N] axis=1 T={T}: {dt*1e3:8.2f} ms",
          flush=True)
    g_rm = jax.jit(
        lambda i: jnp.transpose(jnp.take(bins_rm, i, axis=0)))
    dt, _ = timeit(g_rm, idx_t, n=10)
    print(f"gather row-major [N,F] axis=0 + T   : {dt*1e3:8.2f} ms",
          flush=True)
    g3 = jax.jit(lambda i: jnp.stack([g, h, mask], 1)[i])
    dt, _ = timeit(g3, idx_t, n=10)
    print(f"gather vec3 [N,3]                   : {dt*1e3:8.2f} ms",
          flush=True)


if __name__ == "__main__":
    main()
