"""Synthetic-stream ingestion bench + CPU-smokeable correctness checks.

Two legs, one process:

- **throughput leg** — a :class:`~lightgbm_tpu.ingest.SyntheticSource`
  sized by env (``INGEST_ROWS``, default 120k for the smoke; the
  generator computes chunks on the fly so ``INGEST_ROWS=100000000``
  streams a 10^8-row leg without ever holding the raw matrix), two-pass
  ingested under ``tracemalloc``: the BOUNDED-MEMORY proof asserts the
  peak incremental host allocation stays O(chunk + sample + bin matrix)
  — strictly below half the raw [N, F] f64 bytes the in-RAM path would
  materialize — while the stream is >= 20x the chunk size.  Wall time
  becomes ``ingest_rows_per_s``, trended by ``tools/bench_history.py``
  from the ``INGEST_r*.json`` artifact.
- **correctness leg** — a small distribution-SHIFTED stream (the last
  10% of rows displaced): streamed construction must bit-match the
  in-RAM ``from_matrix`` oracle given the same reservoir sample, chunk
  size must not change the result, and the sample must cover the
  shifted tail (the head-bias regression, ingest/sample.py).

    python tools/ingest_bench.py --json          # one JSON verdict line
    INGEST_ROWS=100000000 INGEST_MEMMAP=1 python tools/ingest_bench.py

``tools/run_suite.py`` runs this as the ``ingest`` tier;
``tools/tpu_window.py`` captures it as the ``bench_ingest`` leg.

Env knobs: ``INGEST_ROWS``, ``INGEST_FEATURES``, ``INGEST_CHUNK_ROWS``,
``INGEST_SAMPLE`` (bin_construct_sample_cnt), ``INGEST_MAXBIN``,
``INGEST_MEMMAP`` (=1 backs the bin matrix with a temp memmap file).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import tempfile
import time
import tracemalloc

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}

# skip the O(N) differential / tracemalloc instrumentation past these
# sizes — the big leg measures throughput, the small leg proves bits
_DIFF_MAX_ROWS = 300_000
_TRACE_MAX_ROWS = 10_000_000


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _next_round(out_dir):
    n = 0
    for f in glob.glob(os.path.join(out_dir, "INGEST_r*.json")):
        m = re.search(r"INGEST_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def _env_int(name, default):
    try:
        return int(float(os.environ.get(name, "") or default))
    except ValueError:
        return default


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Streaming-ingest bench")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    ap.add_argument("--out", default=REPO,
                    help="INGEST_rN.json artifact dir (default: repo root)")
    ap.add_argument("--no-write", action="store_true",
                    help="skip writing the INGEST_rN.json artifact")
    args = ap.parse_args(argv)

    from lightgbm_tpu.config import Config
    from lightgbm_tpu.ingest import (ArraySource, SyntheticSource,
                                     ingest_dataset, ReservoirSampler)
    from lightgbm_tpu.io.dataset import BinnedDataset

    t0 = time.time()
    rows = _env_int("INGEST_ROWS", 120_000)
    features = _env_int("INGEST_FEATURES", 12)
    chunk_rows = _env_int("INGEST_CHUNK_ROWS", 4096)
    sample_cnt = _env_int("INGEST_SAMPLE", 20_000)
    max_bin = _env_int("INGEST_MAXBIN", 63)
    use_memmap = os.environ.get("INGEST_MEMMAP", "") in ("1", "true")

    P = {"verbose": -1, "max_bin": max_bin,
         "bin_construct_sample_cnt": sample_cnt,
         "tpu_ingest_chunk_rows": chunk_rows}
    cfg = Config.from_params(P)
    art = tempfile.mkdtemp(prefix="ingest_bench_")
    memmap_path = os.path.join(art, "X_bin.npy") if use_memmap else None

    # ---- throughput + bounded-memory leg ---------------------------
    src = SyntheticSource(rows, n_features=features,
                          chunk_rows=chunk_rows, seed=0)
    raw_bytes = rows * features * 8
    trace = rows <= _TRACE_MAX_ROWS
    if trace:
        tracemalloc.start()
        tracemalloc.reset_peak()
        base = tracemalloc.get_traced_memory()[0]
    t1 = time.perf_counter()
    ds = ingest_dataset(src, cfg, memmap_path=memmap_path)
    ingest_s = time.perf_counter() - t1
    peak = None
    if trace:
        peak = tracemalloc.get_traced_memory()[1] - base
        tracemalloc.stop()
    rows_per_s = rows / ingest_s if ingest_s > 0 else 0.0

    check("rows_complete", ds.num_data == rows,
          f"{ds.num_data} != {rows}")
    check("stream_20x_chunk", rows >= 20 * chunk_rows,
          f"{rows} rows / {chunk_rows} chunk")
    if trace:
        # O(chunk + sample + bins + the [N] label side array), never the
        # raw [N, F] matrix: half the raw bytes is a hard ceiling with
        # slack over the chunk transposes + sample copies + the label
        # collect/concat/f32 lifecycle (3 x N x 8)
        bin_bytes = 0 if use_memmap else (ds.X_bin.nbytes
                                          if ds.X_bin is not None else 0)
        budget = max(raw_bytes // 2,
                     bin_bytes + 8 * chunk_rows * features * 8
                     + 4 * sample_cnt * features * 8
                     + 3 * rows * 8 + (2 << 20))
        check("bounded_memory", peak < budget,
              f"peak {peak:,} >= budget {budget:,} "
              f"(raw would be {raw_bytes:,})")
        check("raw_never_materialized", peak < raw_bytes,
              f"peak {peak:,} vs raw {raw_bytes:,}")
    check("throughput_recorded", rows_per_s > 0)

    # ---- correctness leg (small, distribution-shifted tail) --------
    diff_rows = min(rows, 40_000)
    if rows > _DIFF_MAX_ROWS:
        print(f"# differential leg runs at {diff_rows} rows "
              f"(INGEST_ROWS={rows} exceeds the {_DIFF_MAX_ROWS} "
              "differential cap — throughput leg stays unchecked for "
              "bits, the small leg proves them)", flush=True)
    dP = dict(P, bin_construct_sample_cnt=2000)
    dcfg = Config.from_params(dP)
    dsrc = SyntheticSource(diff_rows, n_features=features,
                           chunk_rows=1024, seed=3, tail_shift=6.0)
    dds = ingest_dataset(dsrc, dcfg)
    # the oracle sees the SAME rows and the SAME sample
    Xs, ys = [], []
    for Xc, side in dsrc:
        Xs.append(Xc)
        ys.append(side["label"])
    Xfull = np.concatenate(Xs)
    samp = ReservoirSampler(2000, seed=dcfg.data_random_seed)
    for Xc in Xs:
        samp.add(Xc)
    _, idx = samp.finish()
    oracle = BinnedDataset.from_matrix(Xfull, dcfg, sample_indices=idx)
    check("differential_bit_identical",
          np.array_equal(dds.X_bin, oracle.X_bin)
          and np.array_equal(dds.bin_offsets, oracle.bin_offsets))
    # chunk size must not change the constructed dataset
    dds2 = ingest_dataset(
        SyntheticSource(diff_rows, n_features=features,
                        chunk_rows=1024, seed=3, tail_shift=6.0),
        Config.from_params(dict(dP, tpu_ingest_chunk_rows=333)))
    check("chunk_size_invariant", np.array_equal(dds.X_bin, dds2.X_bin))
    # head-bias regression: the sample must cover the shifted tail —
    # a first-2000-rows sample could not place bounds past the shift
    tail0 = int(0.9 * diff_rows)
    frac_tail = float((idx >= tail0).mean())
    m0 = dds.bin_mappers[0]
    top = float(np.asarray(m0.bin_upper_bound)[
        np.isfinite(np.asarray(m0.bin_upper_bound))].max())
    check("sample_covers_tail",
          0.02 < frac_tail < 0.25 and top > 3.0,
          f"tail frac {frac_tail:.3f}, top bound {top:.2f}")

    record = {
        "kind": "ingest",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "backend": "cpu",
        "rows": int(rows),
        "features": int(features),
        "chunk_rows": int(chunk_rows),
        "sample_cnt": int(sample_cnt),
        "memmap": bool(use_memmap),
        "ingest_rows_per_s": round(rows_per_s, 1),
        "ingest_wall_s": round(ingest_s, 3),
        "peak_traced_bytes": int(peak) if peak is not None else None,
        "raw_matrix_bytes": int(raw_bytes),
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "artifacts_dir": art,
    }
    if not args.no_write:
        n = _next_round(args.out)
        path = os.path.join(args.out, f"INGEST_r{n:02d}.json")
        with open(path, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# wrote {path}")
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s, "
              f"{record['ingest_rows_per_s']:,.0f} rows/s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
