"""Tail a training run as one line per boosting iteration (ISSUE 17).

Two sources, same console view:

- **a live run**: point at the train board's base URL (the
  ``tpu_train_metrics_port`` / ``LGBM_TPU_TRAIN_METRICS`` exporter the
  engine arms; the URL is logged at train start) and the watcher polls
  ``GET /progress``, printing each NEW iteration from the ``recent``
  ring plus an ETA/vs-baseline footer when the run finishes or the
  board goes away;
- **a finished (or still-writing) telemetry dir**: point at a
  ``LGBM_TPU_TELEMETRY`` sink (dir or single ``.jsonl``) and the
  watcher renders its ``iteration`` events; ``--follow`` keeps
  re-reading so a live run's sink tails like ``tail -f``.

    python tools/train_watch.py http://127.0.0.1:9187
    python tools/train_watch.py /tmp/telem
    python tools/train_watch.py /tmp/telem --follow

Line format (``format_iteration``)::

    iter    42/500  0.213s  1.23e+07 row-it/s  valid_0.auc=0.9312  [recompiled]

Exit code 0; 1 when the source yields nothing (bad URL / empty dir).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

POLL_S = 0.5          # /progress + --follow poll cadence
_METRIC_KEYS = 2      # metrics shown per line before "..."


def format_iteration(rec: dict, total=None) -> str:
    """One console line for an iteration record — accepts both a board
    ``/progress`` ``recent`` entry and a telemetry ``iteration`` event
    (same field names: iteration / iter_s / metrics / recompiles /
    cum_row_iters_per_s)."""
    it = rec.get("iteration")
    head = f"iter {it if it is not None else '?':>5}"
    if total:
        head += f"/{int(total)}"
    it_s = rec.get("iter_s")
    parts = [head, f"{it_s:.3f}s" if it_s is not None else "?s"]
    rps = rec.get("cum_row_iters_per_s")
    if rps:
        parts.append(f"{float(rps):.2e} row-it/s")
    metrics = rec.get("metrics") or {}
    for k in sorted(metrics)[:_METRIC_KEYS]:
        try:
            parts.append(f"{k}={float(metrics[k]):.4f}")
        except (TypeError, ValueError):
            parts.append(f"{k}={metrics[k]}")
    if len(metrics) > _METRIC_KEYS:
        parts.append("...")
    if rec.get("recompiles"):
        parts.append("[recompiled]")
    return "  ".join(parts)


def _fmt_eta(eta_s) -> str:
    if eta_s is None:
        return "?"
    eta_s = float(eta_s)
    if eta_s >= 3600:
        return f"{eta_s / 3600:.1f}h"
    if eta_s >= 60:
        return f"{eta_s / 60:.1f}m"
    return f"{eta_s:.0f}s"


def _get_json(url: str, timeout: float = 3.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8", "replace"))


def watch_url(base: str, out=sys.stdout, poll_s: float = POLL_S,
              max_s: float = 0.0) -> int:
    """Poll a live board's /progress until the run completes or the
    exporter stops answering; print each new iteration once."""
    base = base.rstrip("/")
    seen = -1
    printed = 0
    last = None
    t0 = time.time()
    misses = 0
    while True:
        try:
            pr = _get_json(base + "/progress")
            misses = 0
        except Exception:
            misses += 1
            if misses >= 3:   # board gone: run finished or URL is wrong
                break
            time.sleep(poll_s)
            continue
        last = pr
        total = pr.get("total_rounds")
        for rec in pr.get("recent") or []:
            it = rec.get("iteration", -1)
            if it is not None and it > seen:
                seen = it
                printed += 1
                print(format_iteration(rec, total=total), file=out)
        it_now = pr.get("iteration")
        if (total and it_now is not None
                and it_now + 1 >= int(total)):
            break
        if max_s and time.time() - t0 > max_s:
            break
        time.sleep(poll_s)
    if last is not None:
        vsb = last.get("vs_baseline")
        print(f"-- iteration {last.get('iteration')}"
              f"/{last.get('total_rounds')}"
              f"  eta {_fmt_eta(last.get('eta_s'))}"
              + (f"  vs_baseline {vsb:.3f}" if vsb else ""), file=out)
    return 0 if printed or last is not None else 1


def watch_path(path: str, out=sys.stdout, follow: bool = False,
               poll_s: float = POLL_S, max_s: float = 0.0) -> int:
    """Render a telemetry sink's iteration events; --follow re-reads the
    file set so a still-writing run tails live.  Re-reading (not seek
    bookkeeping) keeps multi-process sinks (telemetry.{i}.jsonl) simple;
    these files are small."""
    from lightgbm_tpu.obs.report import load_events

    seen = -1
    printed = 0
    t0 = time.time()
    while True:
        events = [e for e in load_events(path)
                  if e.get("event") == "iteration"]
        events.sort(key=lambda e: (e.get("iteration") or 0))
        for e in events:
            it = e.get("iteration", -1)
            if it is not None and it > seen:
                seen = it
                printed += 1
                print(format_iteration(e), file=out)
        if not follow:
            break
        if max_s and time.time() - t0 > max_s:
            break
        time.sleep(poll_s)
    return 0 if printed else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Tail a live run (board URL) or a telemetry dir as "
                    "one line per boosting iteration")
    ap.add_argument("source", help="board base URL (http://host:port) "
                                   "or telemetry dir / .jsonl file")
    ap.add_argument("--follow", action="store_true",
                    help="keep re-reading a telemetry path (live sink)")
    ap.add_argument("--max-seconds", type=float, default=0.0,
                    help="stop watching after this long (0 = until done)")
    args = ap.parse_args(argv)
    if args.source.startswith(("http://", "https://")):
        return watch_url(args.source, max_s=args.max_seconds)
    if not os.path.exists(args.source):
        print(f"error: no such path or URL: {args.source}",
              file=sys.stderr)
        return 1
    return watch_path(args.source, follow=args.follow,
                      max_s=args.max_seconds)


if __name__ == "__main__":
    sys.exit(main())
