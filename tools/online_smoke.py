"""CPU-smokeable end-to-end check of the online learning loop (ISSUE 12).

One process, one minute: train a base model, serve it behind the
registry fleet over HTTP, stream labeled (drifting) rows through the
``OnlineLoop``, and prove the whole closed loop on CPU:

- **ingest → refit → swap**: the loop produces >= 2 refreshed versions,
  each pushed through ``POST /models/{name}/swap`` (the same endpoint an
  external pusher would hit), each passing the canary gate;
- **zero request loss**: concurrent ``POST /predict`` traffic runs
  through every swap — no failed request, every response finite and
  attributable to exactly one model version;
- **fresh models actually move**: post-refresh predictions differ from
  the base model's (the drifted window changed the leaves);
- **poisoned refit is a NON-EVENT**: a deliberately poisoned candidate
  (NaN leaf values) is REJECTED by the canary gate's finite check with
  a 409, and the old version keeps serving.

``tools/run_suite.py`` runs this as the ``online`` tier; the JSON line
carries the per-check verdict map plus ``online_refresh_s`` (mean
refresh wall seconds) and ``online_swap_ok`` (successful pushes) —
``tools/bench_history.py`` trends both from the ``ONLINE_r*.json``
artifact this tool writes.

    python tools/online_smoke.py --json      # one JSON verdict line
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _next_round(out_dir):
    n = 0
    for f in glob.glob(os.path.join(out_dir, "ONLINE_r*.json")):
        m = re.search(r"ONLINE_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def _chunk(rng, n, drift):
    """Labeled rows whose decision boundary shifts with ``drift`` — so a
    refit over a fresh window MUST move the leaf values."""
    X = rng.normal(size=(n, 6))
    y = (X[:, 0] + drift * X[:, 1] - 0.3 * X[:, 2] > drift * 0.5)
    return X, y.astype(np.float64)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Online-loop end-to-end smoke")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    ap.add_argument("--out", default=REPO,
                    help="ONLINE_rN.json artifact dir (default: repo root)")
    ap.add_argument("--no-write", action="store_true",
                    help="skip writing the ONLINE_rN.json artifact")
    args = ap.parse_args(argv)

    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.online import OnlineLoop
    from lightgbm_tpu.serve import ModelRegistry, PredictServer

    t0 = time.time()
    art = tempfile.mkdtemp(prefix="online_smoke_")
    rng = np.random.default_rng(12)

    P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1, "tpu_serve_replicas": 1, "tpu_serve_max_batch": 128,
         "tpu_serve_rollback_watch_s": 0.0, "tpu_online_mode": "refit",
         "tpu_online_window": 1200, "tpu_online_refit_every": 600,
         "tpu_online_decay": 0.5}
    cfg = Config.from_params(P)

    # ---- base model + fleet ----------------------------------------
    X0, y0 = _chunk(rng, 800, drift=0.0)
    ds = lgb.Dataset(X0, label=y0, params=P)
    bst = lgb.train(P, ds, num_boost_round=6, verbose_eval=False)
    base_path = os.path.join(art, "base.txt")
    bst.save_model(base_path)

    reg = ModelRegistry(config=cfg)
    reg.add_model("default", base_path)
    server = PredictServer(reg).start()
    url = server.url
    check("base_deployed", _get(url + "/models")[0] == 200)
    probe = X0[:16]
    base_pred = np.asarray(bst.predict(probe))

    # ---- concurrent traffic through every swap ---------------------
    results, stop = [], threading.Event()

    def traffic():
        while not stop.is_set():
            try:
                code, body = _post(url + "/predict",
                                   {"rows": probe.tolist()}, timeout=60)
                results.append((code, body))
            except Exception as exc:  # noqa: BLE001
                results.append((0, {"error": repr(exc)}))
            time.sleep(0.02)

    t = threading.Thread(target=traffic, daemon=True)
    t.start()

    # ---- the loop: drifting stream -> >= 2 refreshed versions ------
    def push(model_path):
        code, body = _post(f"{url}/models/default/swap",
                           {"model_file": model_path}, timeout=300)
        if code != 200 or not body.get("ok"):
            raise RuntimeError(f"swap bounced: {body}")
        return body

    loop = OnlineLoop(base_path, config=cfg, push=push,
                      workdir=os.path.join(art, "versions"),
                      params=dict(P))
    os.makedirs(loop.workdir, exist_ok=True)
    refresh_s = []
    for round_i, drift in enumerate((0.6, 1.2, 1.8)):
        Xc, yc = _chunk(rng, 600, drift=drift)
        loop.ingest(Xc, yc)
        rep = loop.tick()
        if rep and rep.get("ok"):
            refresh_s.append(rep["ms"] / 1e3)
    stop.set()
    t.join(timeout=10)

    st = loop.stats()
    check("refreshed_at_least_2", st["versions"] >= 2, st)
    code, models = _get(url + "/models")
    live = next((m for m in models["models"]
                 if m["name"] == "default"), {})
    check("registry_live_advanced",
          (live.get("live_version") or 0) >= 3, live)
    bad = [r for r in results if r[0] != 200]
    check("zero_request_loss", len(bad) == 0 and len(results) > 0,
          f"{len(bad)}/{len(results)} failed: {bad[:2]}")
    vals = [np.asarray(b.get("predictions")) for c, b in results if c == 200]
    check("predictions_finite",
          all(np.isfinite(v).all() for v in vals))
    versions_seen = {b.get("version") for c, b in results if c == 200}
    check("versions_attributed", None not in versions_seen
          and len(versions_seen) >= 2, versions_seen)
    moved = float(np.max(np.abs(np.asarray(vals[-1]) - base_pred))) \
        if vals else 0.0
    check("fresh_model_moved", moved > 1e-6, f"max delta {moved}")

    # ---- poisoned refit: canary gate rejects, old version serves ---
    with open(loop.base) as fh:
        txt = fh.read()
    poisoned = os.path.join(art, "poisoned.txt")
    with open(poisoned, "w") as fh:
        fh.write(re.sub(r"^leaf_value=.*$",
                        lambda m: "leaf_value=" + " ".join(
                            ["nan"] * len(m.group(0).split("=")[1].split())),
                        txt, flags=re.MULTILINE))
    live_before = _get(url + "/models")[1]["models"][0]["live_version"]
    try:
        code, body = _post(f"{url}/models/default/swap",
                           {"model_file": poisoned}, timeout=300)
        check("poisoned_rejected_409", False, f"swap answered {code}")
    except urllib.error.HTTPError as exc:
        body = json.loads(exc.read())
        check("poisoned_rejected_409", exc.code == 409, body)
        rep = (body.get("report") or {}).get("checks") or {}
        check("poisoned_canary_finite_false",
              rep.get("finite") is False or rep.get("gate") is False, body)
    live_after = _get(url + "/models")[1]["models"][0]["live_version"]
    check("old_version_still_serving", live_after == live_before,
          f"{live_before} -> {live_after}")
    code, body = _post(url + "/predict", {"rows": probe.tolist()})
    check("serving_after_poison", code == 200
          and np.isfinite(body["predictions"]).all())

    server.stop(close_session=True)

    record = {
        "kind": "online",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "backend": "cpu",
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "online_refresh_s": (round(sum(refresh_s) / len(refresh_s), 3)
                             if refresh_s else None),
        "online_swap_ok": st["versions"],
        "online_swap_rejected": st["rejected"] + 1,  # + the poisoned push
        "rows_ingested": st["rows_ingested"],
        "artifacts_dir": art,
    }
    if not args.no_write:
        n = _next_round(args.out)
        path = os.path.join(args.out, f"ONLINE_r{n:02d}.json")
        with open(path, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# wrote {path}")
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
