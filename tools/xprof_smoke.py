"""Measured-roofline plane smoke — the ``xprof`` suite tier (ISSUE 18).

Runs a short CPU train with the capture window + compile observer
armed (``LGBM_TPU_XPROF``, telemetry sink, metrics board, persistent
compile cache all on), then proves the plane end to end:

- **trace_captured**: the windowed ``jax.profiler`` capture produced
  at least one ``.trace.json.gz`` artifact and parsed cleanly;
- **kernels_attributed**: >= 3 distinct ``lgbm/*`` kernels with
  nonzero measured ms (plus the ``unattributed`` device residual);
- **model_joined**: at least one attributed kernel carries the
  analytic-model join (model_ms / roofline_frac / bound);
- **events_validate**: the emitted ``kernel_measured`` + ``compile``
  events pass ``report_mod.validate_events`` against their schemas;
- **digest_renders**: ``report.render`` of the sink digest contains
  the measured-roofline table and the compile-plane line;
- **compile_observed / cache_counted**: backend-compile walls and
  persistent-cache misses landed in the compile digest;
- **board_compile_metrics**: cache hit/miss + retrace gauges and the
  per-jit compile walls are visible in the board's ``/metrics`` text;
- **overhead_ok**: off-window ``step()`` accounting stays under 5% of
  train wall — the same off-path guard board_smoke.py pins.

The train shape is deliberately tiny: on the CPU backend the thunk
executor emits one TraceMe per HLO op per while-loop iteration, so
capture volume (and stop_trace export time) scales with row count.

    python tools/xprof_smoke.py --json

Last stdout line is the ``{"ok": ..., "checks": ...}`` verdict map
(the tools/run_suite.py tool-tier contract).  Exit 0 iff all pass.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

ROUNDS = 6
WINDOW_ITERS = 2


def _fetch(url: str, timeout: float = 3.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def run_smoke() -> dict:
    work = tempfile.mkdtemp(prefix="lgbm_xprof_smoke_")
    telem = os.path.join(work, "telem")
    # env overrides beat outer settings so the smoke can't be disarmed
    os.environ["LGBM_TPU_XPROF"] = str(WINDOW_ITERS)
    os.environ["LGBM_TPU_TELEMETRY"] = telem
    os.environ["LGBM_TPU_TRAIN_METRICS"] = "0"  # ephemeral board port
    # a COLD persistent compile cache: every compile is a recorded miss
    os.environ["LGBM_TPU_COMPILE_CACHE"] = os.path.join(work, "cc")

    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.obs import board, xprof
    import importlib
    report_mod = importlib.import_module('lightgbm_tpu.obs.report')

    if not obs.enabled():  # env gate ran at import; belt-and-braces
        obs.enable(telem)

    rng = np.random.default_rng(7)
    X = rng.normal(size=(500, 10))
    y = (X[:, 0] + 0.4 * X[:, 1] - 0.2 * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "verbose": -1,
              "tpu_train_metrics_port": 0}
    ds = lgb.Dataset(X, label=y, params=params)

    state = {"metrics": None}

    # scrape /metrics once mid-train via a callback — the board dies
    # with the run and the compile gauges only exist while it serves
    def scrape(env):
        if state["metrics"] is None and env.iteration >= 2:
            b = board.current()
            if b is not None and b.port:
                try:
                    state["metrics"] = _fetch(b.url + "/metrics").decode()
                except Exception:
                    pass

    t0 = time.perf_counter()
    lgb.train(params, ds, num_boost_round=ROUNDS, callbacks=[scrape])
    wall = time.perf_counter() - t0

    digest = obs.digest()
    xp = digest.get("xprof") or {}
    comp = digest.get("compile") or {}

    checks = {}
    checks["trace_captured"] = (xp.get("trace_files", 0) > 0
                                and xp.get("trace_parsed", 0) > 0
                                and not xp.get("errors"))
    lgbm_kernels = {k: v for k, v in (xp.get("kernels") or {}).items()
                    if k.startswith("lgbm/") and v.get("measured_ms", 0) > 0}
    checks["kernels_attributed"] = len(lgbm_kernels) >= 3
    checks["model_joined"] = any(
        v.get("roofline_frac") is not None for v in lgbm_kernels.values())

    events = report_mod.load_events(telem)
    emitted = [e for e in events
               if e.get("event") in ("kernel_measured", "compile")]
    problems = report_mod.validate_events(
        events, kinds=("kernel_measured", "compile"))
    checks["events_validate"] = bool(emitted) and not problems

    rendered = report_mod.render(report_mod.summarize(events))
    checks["digest_renders"] = ("measured roofline" in rendered
                                and "compile plane" in rendered)

    checks["compile_observed"] = (comp.get("compiles", 0) > 0
                                  and comp.get("wall_s", 0) > 0
                                  and bool(comp.get("by_jit")))
    checks["cache_counted"] = comp.get("cache_misses", 0) > 0

    mtext = state["metrics"] or ""
    checks["board_compile_metrics"] = all(
        name in mtext for name in ("tpu_train_compile_cache_hits_total",
                                   "tpu_train_compile_cache_misses_total",
                                   "tpu_train_retraces_total",
                                   "tpu_train_compile_seconds_total"))

    # off-window overhead: re-run the same shape with the window pushed
    # past the horizon, so every step() takes the disarmed branch
    win = xprof.WindowedCapture(os.path.join(work, "never"),
                                iters=1, skip=10 ** 9)
    t1 = time.perf_counter()
    bst2 = lgb.Booster(params=params, train_set=ds)
    for _ in range(ROUNDS):
        bst2.update()
        win.step()
    wall2 = time.perf_counter() - t1
    checks["overhead_ok"] = win.hook_s < 0.05 * wall2

    return {
        "kind": "xprof",
        "t": round(time.time(), 1),
        "rounds": ROUNDS,
        "window_iters": WINDOW_ITERS,
        "wall_s": round(wall, 3),
        "hook_s": round(win.hook_s, 6),
        "window_ms": xp.get("window_ms"),
        "kernels": sorted(lgbm_kernels),
        "kernel_measured_events": sum(
            1 for e in emitted if e.get("event") == "kernel_measured"),
        "compiles": comp.get("compiles"),
        "cache_misses": comp.get("cache_misses"),
        "validate_problems": problems[:5],
        "checks": checks,
        "ok": all(checks.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Capture->parse->attribute CPU smoke (xprof tier)")
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON verdict line")
    args = ap.parse_args(argv)
    record = run_smoke()
    if not args.json:
        for k, v in record["checks"].items():
            print(f"  {'PASS' if v else 'FAIL'}  {k}")
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
