"""Serving bench: closed-loop + open-loop (Poisson) latency/throughput.

The training benches (bench.py) answer "how fast does it learn"; this
answers "how does it serve" — the serve/ subsystem's round artifact:

1. **closed-loop**: N client threads fire mixed-size requests
   back-to-back through ``PredictorSession.submit``/``result`` for a
   fixed duration — the saturation number (rows/s, request p50/p99).
2. **open-loop**: requests arrive on a Poisson clock at a fixed rate
   with mixed sizes, so latency includes real queueing delay instead of
   the closed-loop's self-throttling — the SLO number.  With
   ``--explain-frac p`` (or SERVE_EXPLAIN_FRAC) a fraction ``p`` of the
   Poisson arrivals are ``submit_explain`` TreeSHAP requests riding
   their own microbatch queue — the mixed-load leg that writes
   ``explain_p99`` into the artifact.
3. **swap leg** (default on; ``SERVE_SWAP=0`` disables): a multi-model
   Poisson mix over a registry fleet (models ``a``+``b``,
   ``SERVE_REPLICAS`` sessions each) with a canary-gated hot swap of
   model ``a`` mid-run — records ``swap_blip_p99_ms`` (p99 of requests
   completing inside the swap window) vs ``steady_p99_ms`` and the
   rollback count; ``bench_history.py`` trends both and flags a blip
   worse than 2x steady.
4. **cold-start leg** (default on; ``SERVE_COLDSTART=0`` disables): a
   FRESH SUBPROCESS boots against the serialized-executable store
   (serve/aot.py) and answers request #1 — time-to-first-response and
   request-#1 latency, A/B'd AOT-on vs AOT-off.  Records
   ``serve_coldstart_ms`` (the on number) which ``bench_history.py``
   trends, plus the cold compile count: zero with the store armed, the
   full pow2 family without it.
5. **arena leg** (default on; ``SERVE_ARENA=0`` disables): SERVE_TENANTS
   tenant models under a heavy-tail (Zipf) request mix at batch-starved
   sizes (1-4 rows), served closed-loop twice — once by dedicated
   per-model ``PredictorSession``s, once by one ``ForestArena`` with
   cross-model microbatching — and records the throughput ratio as
   ``speedup`` (bench_history trends it) plus per-tenant parity.
6. **HTTP smoke** (``--smoke``): starts ``PredictServer`` in-process,
   fires concurrent mixed-size POST /predict + GET /health, then
   asserts p99 recorded, the compile count bounded by the pow2 bucket
   set (<= ceil(log2(max_batch)) + 1), zero request loss across the
   swap leg, and a clean shutdown.  This is the ``serve`` leg
   ``tools/run_suite.py`` runs in CI.

Writes ``SERVE_r{N}.json`` (``--out``/``--round``; ``--json`` prints the
record instead) which ``tools/bench_history.py`` folds into the
trajectory table.  CPU-runnable end to end; on a TPU window
``tools/tpu_window.py`` captures the same record as
``SERVE_manual_r{N}.json``.

Env knobs (smoke sizes in parens): SERVE_ROWS train rows (2000),
SERVE_TREES boosting rounds (20), SERVE_FEATURES (8), SERVE_MAX_BATCH
(256), SERVE_CLIENTS closed-loop threads (4), SERVE_DURATION_S per-loop
seconds (2), SERVE_RATE open-loop req/s (50), SERVE_EXPLAIN_FRAC
fraction of open-loop arrivals that are /explain requests (0.2 smoke,
0.1 full), SERVE_TENANTS arena-leg tenant models (4 smoke, 8 full),
SERVE_ARENA_REQS arena-leg request count (240 smoke, 1600 full),
SERVE_MODEL serve an existing model file instead of training one.
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import re
import sys
import tempfile
import threading
import time
from typing import Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

_DEFAULTS = dict(rows=20000, trees=60, features=12, max_batch=1024,
                 clients=8, duration_s=5.0, rate=200.0,
                 explain_frac=0.1, tenants=8, arena_reqs=1600)
_SMOKE = dict(rows=2000, trees=20, features=8, max_batch=256,
              clients=4, duration_s=2.0, rate=50.0, explain_frac=0.2,
              tenants=4, arena_reqs=240)


def _env(name, cast, fallback):
    v = os.environ.get(name, "")
    if v:
        try:
            return cast(v)
        except ValueError:
            pass
    return fallback


def knobs(smoke: bool) -> dict:
    base = dict(_SMOKE if smoke else _DEFAULTS)
    return dict(
        rows=_env("SERVE_ROWS", int, base["rows"]),
        trees=_env("SERVE_TREES", int, base["trees"]),
        features=_env("SERVE_FEATURES", int, base["features"]),
        max_batch=_env("SERVE_MAX_BATCH", int, base["max_batch"]),
        clients=_env("SERVE_CLIENTS", int, base["clients"]),
        duration_s=_env("SERVE_DURATION_S", float, base["duration_s"]),
        rate=_env("SERVE_RATE", float, base["rate"]),
        explain_frac=_env("SERVE_EXPLAIN_FRAC", float,
                          base["explain_frac"]),
        tenants=_env("SERVE_TENANTS", int, base["tenants"]),
        arena_reqs=_env("SERVE_ARENA_REQS", int, base["arena_reqs"]),
        model=os.environ.get("SERVE_MODEL", ""),
    )


def build_model(k: dict, workdir: str, name: str = "serve_bench_model.txt",
                num_leaves: int = 31, trees: Optional[int] = None,
                seed: int = 7) -> str:
    """Train a small binary model (NaN-heavy + categorical, so the bench
    exercises the full binning surface) and save it; or reuse
    SERVE_MODEL.  ``name``/``num_leaves``/``trees``/``seed`` let the
    swap leg train model VARIANTS over the same feature space."""
    if k["model"] and name == "serve_bench_model.txt":
        return k["model"]
    import numpy as np

    import lightgbm_tpu as lgb
    rng = np.random.default_rng(seed)
    F = k["features"]
    Xnum = rng.normal(size=(k["rows"], F - 1))
    Xnum[rng.random(Xnum.shape) < 0.05] = np.nan
    Xcat = rng.integers(0, 16, size=(k["rows"], 1)).astype(np.float64)
    X = np.hstack([Xnum, Xcat])
    y = ((np.nan_to_num(Xnum[:, 0]) + 0.25 * (Xcat[:, 0] % 3)) > 0
         ).astype(np.float64)
    params = {"objective": "binary", "num_leaves": num_leaves,
              "verbose": -1, "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, label=y, categorical_feature=[F - 1], params=params)
    bst = lgb.train(params, ds,
                    num_boost_round=trees if trees else k["trees"])
    path = os.path.join(workdir, name)
    bst.save_model(path)
    return path


def _percentiles(lat):
    # the one shared nearest-rank definition (obs/report.py) so the
    # bench record can't diverge from the digest / health endpoint
    from lightgbm_tpu.obs.report import percentile
    lat = sorted(lat)
    return percentile(lat, 0.50), percentile(lat, 0.99)


def _request_sizes(rng, max_batch: int):
    """Mixed request sizes: mostly small single-user lookups, a tail of
    bulk scoring calls — the traffic shape the microbatcher exists for."""
    import numpy as np
    if rng.random() < 0.8:
        return int(rng.integers(1, 17))
    return int(rng.integers(17, max(max_batch // 2, 18)))


def closed_loop(sess, Xpool, k: dict) -> dict:
    import numpy as np
    stop_at = time.perf_counter() + k["duration_s"]
    lat, rows_done, errors = [], [0], []
    lock = threading.Lock()

    def client(seed):
        rng = np.random.default_rng(seed)
        while time.perf_counter() < stop_at:
            n = _request_sizes(rng, k["max_batch"])
            lo = int(rng.integers(0, max(Xpool.shape[0] - n, 1)))
            t0 = time.perf_counter()
            try:
                ticket = sess.submit(Xpool[lo:lo + n])
                sess.result(ticket, timeout=60.0)
            except Exception as exc:  # noqa: BLE001
                errors.append(f"{type(exc).__name__}: {exc}")
                continue
            with lock:
                lat.append((time.perf_counter() - t0) * 1e3)
                rows_done[0] += n

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(s,))
               for s in range(k["clients"])]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    p50, p99 = _percentiles(lat)
    return {"clients": k["clients"], "duration_s": round(wall, 2),
            "requests": len(lat), "rows": rows_done[0],
            "req_per_s": round(len(lat) / wall, 1),
            "rows_per_s": round(rows_done[0] / wall, 1),
            "p50_ms": p50, "p99_ms": p99, "errors": len(errors),
            "error_sample": errors[:3]}


def open_loop(sess, Xpool, k: dict) -> dict:
    """Poisson arrivals at SERVE_RATE req/s; latency measured from the
    scheduled submit to future completion, so queueing delay counts.
    A fraction ``explain_frac`` of the arrivals are ``submit_explain``
    TreeSHAP requests riding their own microbatch queue — the mixed
    load that makes ``explain_p99`` an under-contention number instead
    of an idle-path one."""
    import numpy as np
    rng = np.random.default_rng(11)
    lat, overloads, failures = [], [0], [0]
    xlat, xfailures = [], [0]
    lock = threading.Lock()
    pending = []
    stop_at = time.perf_counter() + k["duration_s"]
    from lightgbm_tpu.serve import ServeOverloadError
    xfrac = (min(max(k.get("explain_frac", 0.0), 0.0), 1.0)
             if getattr(sess, "explain_enabled", False) else 0.0)

    def on_done(t0, sink, fail):
        def cb(fut):
            with lock:
                if fut.exception() is None:
                    sink.append((time.perf_counter() - t0) * 1e3)
                else:
                    fail[0] += 1
        return cb

    n_sent, x_sent = 0, 0
    while time.perf_counter() < stop_at:
        gap = rng.exponential(1.0 / max(k["rate"], 1e-6))
        time.sleep(gap)
        explain = rng.random() < xfrac
        n = _request_sizes(rng, k["max_batch"])
        lo = int(rng.integers(0, max(Xpool.shape[0] - n, 1)))
        t0 = time.perf_counter()
        try:
            if explain:
                ticket = sess.submit_explain(Xpool[lo:lo + n])
            else:
                ticket = sess.submit(Xpool[lo:lo + n])
        except ServeOverloadError:
            overloads[0] += 1
            continue
        if explain:
            x_sent += 1
            cb = on_done(t0, xlat, xfailures)
        else:
            n_sent += 1
            cb = on_done(t0, lat, failures)
        for fut, _ in ticket.parts:
            fut.add_done_callback(cb)
            pending.append(fut)
    deadline = time.time() + 30
    for fut in pending:
        try:
            fut.result(max(deadline - time.time(), 0.1))
        except Exception:  # noqa: BLE001 — on_done already counted it;
            pass           # a failed request must not kill the bench
    p50, p99 = _percentiles(lat)
    out = {"rate_rps": k["rate"], "requests": n_sent,
           "completed": len(lat), "overloads": overloads[0],
           "failures": failures[0], "p50_ms": p50, "p99_ms": p99,
           "explain_frac": xfrac}
    if xfrac > 0:
        xp50, xp99 = _percentiles(xlat)
        out.update(explain_requests=x_sent, explain_completed=len(xlat),
                   explain_failures=xfailures[0],
                   explain_p50_ms=xp50, explain_p99_ms=xp99)
    return out


def http_smoke(server, Xpool, k: dict) -> dict:
    """Concurrent mixed-size POST /predict + GET /health over real HTTP,
    with a poller hammering /metrics and /debug/flight THROUGHOUT the
    storm — the introspection endpoints must answer under load, not just
    on an idle server (run_suite.py's serve tier gates on this)."""
    import urllib.request

    import numpy as np
    url = server.url
    lat, errors = [], []
    poll = {"metrics": 0, "flight": 0, "explain": 0, "errors": []}
    done = threading.Event()
    lock = threading.Lock()

    xfrac = (min(max(k.get("explain_frac", 0.0), 0.0), 1.0)
             if getattr(server.session, "explain_enabled", False) else 0.0)

    def post(seed):
        rng = np.random.default_rng(seed)
        for _ in range(4):
            explain = rng.random() < xfrac
            n = _request_sizes(rng, k["max_batch"])
            lo = int(rng.integers(0, max(Xpool.shape[0] - n, 1)))
            body = json.dumps(
                {"rows": Xpool[lo:lo + n].tolist()}).encode()
            path = "/explain" if explain else "/predict"
            req = urllib.request.Request(
                url + path, data=body,
                headers={"Content-Type": "application/json",
                         "X-Request-Id": f"smoke-{seed}-{n}"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    payload = json.loads(resp.read())
                field = "contributions" if explain else "predictions"
                if len(payload[field]) != n:
                    raise ValueError("row count mismatch")
                with lock:
                    lat.append((time.perf_counter() - t0) * 1e3)
                    if explain:
                        poll["explain"] += 1
            except Exception as exc:  # noqa: BLE001
                with lock:
                    errors.append(f"{type(exc).__name__}: {exc}")

    def poller():
        from lightgbm_tpu.serve.metrics import parse_prometheus
        while not done.is_set():
            try:
                with urllib.request.urlopen(url + "/metrics",
                                            timeout=30) as resp:
                    pm = parse_prometheus(resp.read().decode())
                if "tpu_serve_slo_burn" in pm:
                    poll["metrics"] += 1
                with urllib.request.urlopen(url + "/debug/flight",
                                            timeout=30) as resp:
                    fl = json.loads(resp.read())
                if isinstance(fl.get("events"), list):
                    poll["flight"] += 1
            except Exception as exc:  # noqa: BLE001
                poll["errors"].append(f"{type(exc).__name__}: {exc}")
            done.wait(0.05)

    threads = [threading.Thread(target=post, args=(s,))
               for s in range(k["clients"])]
    pt = threading.Thread(target=poller)
    pt.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done.set()
    pt.join(30)
    with urllib.request.urlopen(url + "/health", timeout=10) as resp:
        health = json.loads(resp.read())
    p50, p99 = _percentiles(lat)
    return {"requests": len(lat), "errors": errors[:5],
            "p50_ms": p50, "p99_ms": p99, "health": health,
            "explain_requests": poll["explain"],
            "metrics_polls": poll["metrics"],
            "flight_polls": poll["flight"],
            "poll_errors": poll["errors"][:5]}


def swap_leg(k: dict, workdir: str, model_a: str) -> dict:
    """Multi-model Poisson mix with a hot-swap mid-run (ROADMAP item 3):
    two models serve behind the registry, Poisson arrivals split across
    them, and halfway through model 'a' hot-swaps to a retrained
    variant.  The artifact records ``swap_blip_p99_ms`` — the p99 of
    requests completing inside the swap window (pack + canary + flip +
    fresh-bucket compiles) — against ``steady_p99_ms``, plus the
    registry's rollback count.  ``bench_history.py`` trends both and
    flags a blip worse than 2x steady."""
    import numpy as np
    from lightgbm_tpu.serve import ModelRegistry, ServeOverloadError
    model_b = build_model(k, workdir, name="serve_bench_model_b.txt",
                          num_leaves=15, seed=11)
    model_a2 = build_model(k, workdir, name="serve_bench_model_a2.txt",
                           num_leaves=23, seed=13)
    reps = _env("SERVE_REPLICAS", int, 1)
    reg = ModelRegistry(n_replicas=reps, max_batch=k["max_batch"],
                        max_wait_ms=2.0)
    reg.add_model("a", model_a)
    reg.add_model("b", model_b)
    for name in ("a", "b"):
        reg.resolve(name).router.warmup()
    rng = np.random.default_rng(23)
    F = k["features"]
    Xpool = np.hstack([rng.normal(size=(2048, F - 1)),
                       rng.integers(-1, 20, size=(2048, 1)
                                    ).astype(np.float64)])
    lock = threading.Lock()
    done = []            # (t_complete, lat_ms, ok)
    pending = []
    overloads = 0
    n_sent = 0
    by_model = {"a": 0, "b": 0}
    duration = k["duration_s"] * 2
    t_begin = time.perf_counter()
    stop_at = t_begin + duration
    swap_at = t_begin + duration / 2
    swap_info = {}

    def do_swap():
        t0 = time.perf_counter()
        try:
            rep = reg.swap("a", model_a2)
            swap_info.update(ok=bool(rep.get("ok")),
                             to_version=rep.get("to_version"))
        except Exception as exc:  # noqa: BLE001 — leg must finish
            swap_info.update(ok=False,
                             error=f"{type(exc).__name__}: {exc}")
        swap_info.update(t0=t0, t1=time.perf_counter())

    swap_thread = None
    while time.perf_counter() < stop_at:
        time.sleep(rng.exponential(1.0 / max(k["rate"], 1e-6)))
        if swap_thread is None and time.perf_counter() >= swap_at:
            swap_thread = threading.Thread(target=do_swap)
            swap_thread.start()
        model = "a" if rng.random() < 0.7 else "b"
        n = _request_sizes(rng, k["max_batch"])
        lo = int(rng.integers(0, max(Xpool.shape[0] - n, 1)))
        t0 = time.perf_counter()
        try:
            ticket = reg.submit(Xpool[lo:lo + n], model=model)
        except ServeOverloadError:
            overloads += 1
            continue
        n_sent += 1
        by_model[model] += 1

        def cb(fut, t0=t0):
            with lock:
                done.append((time.perf_counter(),
                             (time.perf_counter() - t0) * 1e3,
                             fut.exception() is None))
        for fut, _ in ticket.parts:
            fut.add_done_callback(cb)
            pending.append(fut)
    if swap_thread is None:
        do_swap()
    else:
        swap_thread.join(120)
    deadline = time.time() + 60
    for fut in pending:
        try:
            fut.result(max(deadline - time.time(), 0.1))
        except Exception:  # noqa: BLE001 — cb already counted it
            pass
    s0, s1 = swap_info.get("t0", swap_at), swap_info.get("t1", swap_at)
    with lock:
        # steady = completions strictly BEFORE the swap began (a clean
        # baseline no flip cost can pollute); blip = completions from
        # swap start until 1s past the flip — where pack/canary/warmup
        # contention and any leaked compiles would land
        steady = [lat for t, lat, ok in done if ok and t < s0]
        blip = [lat for t, lat, ok in done if ok and s0 <= t <= s1 + 1.0]
        failures = sum(1 for _, _, ok in done if not ok)
    rollbacks = sum(m["rollbacks"] for m in reg.models())
    reg.close()
    sp50, sp99 = _percentiles(steady)
    _, bp99 = _percentiles(blip)
    return {
        "rate_rps": k["rate"], "requests": n_sent,
        "completed": len(done), "failures": failures,
        "overloads": overloads, "by_model": by_model,
        "replicas": reps,
        "swap_ok": swap_info.get("ok"),
        "swap_error": swap_info.get("error"),
        "swap_ms": round((s1 - s0) * 1e3, 1),
        "swap_window_requests": len(blip),
        "steady_p50_ms": sp50, "steady_p99_ms": sp99,
        "swap_blip_p99_ms": bp99,
        "rollbacks": rollbacks,
    }


# the cold-boot measurement runs in a FRESH interpreter: imports, model
# load, session construction (which loads the persisted executables when
# $LGBM_TPU_SERVE_AOT_DIR points at a warmed store), request #1, then a
# full pow2 sweep — printing one JSON line the parent A/B-compares
_COLD_CHILD = r"""
import json, sys, time
t0 = time.perf_counter()
import numpy as np
sys.path.insert(0, sys.argv[1])
from lightgbm_tpu import obs
from lightgbm_tpu.serve import PredictorSession
model_path, xpath, max_batch = sys.argv[2], sys.argv[3], int(sys.argv[4])
obs.install_recompile_hook()
c0 = obs.compile_count()
sess = PredictorSession(model_path, max_batch=max_batch, max_wait_ms=1.0)
X = np.load(xpath)
t1 = time.perf_counter()
out1 = sess.predict(X[:16])
t2 = time.perf_counter()
n = 1
while n <= max_batch:
    sess.predict(X[:n])
    n *= 2
aot = sess.stats().get("aot") or {}
print(json.dumps({
    "boot_to_first_ms": round((t2 - t0) * 1e3, 1),
    "request1_ms": round((t2 - t1) * 1e3, 2),
    "compiles": int(obs.compile_count() - c0),
    "aot_buckets": len(aot.get("buckets") or []),
    "probe": np.asarray(out1, dtype=np.float64).tolist(),
}))
sess.close()
"""


def coldstart_leg(k: dict, workdir: str, model_path: str, Xpool) -> dict:
    """Fresh-subprocess cold start, AOT-on vs AOT-off (ISSUE 19): the
    parent warms the executable store once, then boots two children —
    one pointed at the store, one without it.  ``serve_coldstart_ms``
    is the AOT-on time from exec to request-#1 response; the off run is
    the JIT baseline the store exists to delete.  A zero cold compile
    count across the full pow2 sweep is the tentpole's contract."""
    import subprocess

    import numpy as np
    from lightgbm_tpu.serve import PredictorSession
    aot_dir = os.path.join(workdir, "aot_store")
    warm = PredictorSession(model_path, max_batch=k["max_batch"],
                            max_wait_ms=1.0,
                            config={"tpu_serve_aot_dir": aot_dir,
                                    "verbose": -1})
    warm.warmup()
    warm_stats = (warm.stats().get("aot") or {})
    warm.close()
    xpath = os.path.join(workdir, "coldstart_X.npy")
    np.save(xpath, np.ascontiguousarray(Xpool[:max(k["max_batch"], 16)]))

    def boot(aot_on: bool) -> dict:
        env = dict(os.environ)
        env.pop("LGBM_TPU_SERVE_AOT_DIR", None)
        if aot_on:
            env["LGBM_TPU_SERVE_AOT_DIR"] = aot_dir
        t0 = time.perf_counter()
        proc = subprocess.run(
            [sys.executable, "-c", _COLD_CHILD, REPO, model_path, xpath,
             str(k["max_batch"])],
            capture_output=True, text=True, env=env, timeout=600)
        wall_ms = round((time.perf_counter() - t0) * 1e3, 1)
        lines = [ln for ln in proc.stdout.splitlines()
                 if ln.startswith("{")]
        if proc.returncode != 0 or not lines:
            return {"error": (proc.stderr or proc.stdout)[-500:],
                    "wall_ms": wall_ms}
        rec = json.loads(lines[-1])
        rec["wall_ms"] = wall_ms
        return rec

    on, off = boot(True), boot(False)
    probe_on, probe_off = on.pop("probe", None), off.pop("probe", None)
    return {
        "store_entries": warm_stats.get("entries"),
        "aot_on": on, "aot_off": off,
        # the headline numbers bench_history.py trends
        "serve_coldstart_ms": on.get("boot_to_first_ms"),
        "serve_coldstart_off_ms": off.get("boot_to_first_ms"),
        "request1_ms": on.get("request1_ms"),
        "request1_off_ms": off.get("request1_ms"),
        "cold_compiles": on.get("compiles"),
        "cold_compiles_off": off.get("compiles"),
        # the AOT path must change WHEN, never WHAT: request #1 through
        # a deserialized executable is bit-identical to the JIT path
        "bit_identical": (probe_on == probe_off
                          if probe_on is not None and probe_off is not None
                          else None),
    }


def arena_leg(k: dict, workdir: str, Xpool) -> dict:
    """Heavy-tail multi-tenant serving, arena vs per-model sessions
    (ISSUE 19): SERVE_TENANTS models, request mix Zipf over tenants at
    batch-starved sizes (1-4 rows), identical closed-loop work-list
    through both data planes.  Per-model sessions each coalesce only
    their own trickle; the arena coalesces the CROSS-model stream into
    shared device dispatches — ``speedup`` is the throughput ratio
    bench_history.py trends (>= 1.5x is the ISSUE 19 target)."""
    import numpy as np
    from lightgbm_tpu.serve import ForestArena, PredictorSession
    T = max(k["tenants"], 2)
    paths = [build_model(k, workdir, name=f"arena_tenant_{i}.txt",
                         num_leaves=11 + 2 * (i % 5),
                         trees=max(k["trees"] // 3, 5), seed=100 + i)
             for i in range(T)]
    # Zipf-ish tenant popularity: p(i) ~ 1/(i+1)^1.2 — one hot tenant,
    # a long cold tail, the mix that starves per-model batches
    w = (np.arange(T) + 1.0) ** -1.2
    p = w / w.sum()
    rng = np.random.default_rng(29)
    reqs = []
    for _ in range(max(k["arena_reqs"], 8)):
        n = int(rng.integers(1, 5))
        lo = int(rng.integers(0, max(Xpool.shape[0] - n, 1)))
        reqs.append((int(rng.choice(T, p=p)), n, lo))

    def run(call):
        idx = [0]
        lat, rows, failures = [], [0], [0]
        lock = threading.Lock()

        def client():
            while True:
                with lock:
                    if idx[0] >= len(reqs):
                        return
                    ti, n, lo = reqs[idx[0]]
                    idx[0] += 1
                t0 = time.perf_counter()
                try:
                    call(ti, Xpool[lo:lo + n])
                except Exception:  # noqa: BLE001 — counted below
                    with lock:
                        failures[0] += 1
                    continue
                with lock:
                    lat.append((time.perf_counter() - t0) * 1e3)
                    rows[0] += n

        t0 = time.perf_counter()
        threads = [threading.Thread(target=client)
                   for _ in range(k["clients"])]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        p50, p99 = _percentiles(lat)
        return {"wall_s": round(wall, 2),
                "req_per_s": round(len(lat) / wall, 1),
                "rows_per_s": round(rows[0] / wall, 1),
                "p50_ms": p50, "p99_ms": p99, "failures": failures[0]}

    # side A: one dedicated session per tenant (the per-model baseline)
    solo = {i: PredictorSession(paths[i], max_batch=k["max_batch"],
                                max_wait_ms=2.0) for i in range(T)}
    for s in solo.values():
        s.warmup()

    def solo_call(ti, X):
        sess = solo[ti]
        sess.result(sess.submit(X), timeout=60.0)

    solo_res = run(solo_call)

    # side B: one arena, every tenant resident, one shared microbatcher
    arena = ForestArena(max_batch=k["max_batch"], max_wait_ms=2.0)
    for i in range(T):
        arena.admit(f"t{i}", paths[i])
    arena.warmup()

    def arena_call(ti, X):
        arena.result(arena.submit(X, model=f"t{ti}"), timeout=60.0)

    arena_res = run(arena_call)

    # per-tenant parity: one data plane, two routes, identical answers
    probe = Xpool[:32]
    parity = all(
        np.array_equal(arena.predict(probe, model=f"t{i}"),
                       solo[i].predict(probe)) for i in range(T))
    st = arena.stats()
    for s in solo.values():
        s.close()
    arena.close()
    base = max(solo_res["rows_per_s"], 1e-9)
    return {
        "tenants": T, "requests": len(reqs), "zipf_exp": 1.2,
        "solo": solo_res, "arena": arena_res,
        "speedup": round(arena_res["rows_per_s"] / base, 3),
        "parity": bool(parity),
        "batches": st["batches"],
        "cross_model_batches": st["cross_model_batches"],
        "occupancy": st["occupancy"],
    }


def scrape_metrics(server) -> dict:
    """One end-of-run /metrics scrape, parsed (the server-side view
    embedded in SERVE_rN.json next to the client-observed numbers)."""
    import urllib.request
    from lightgbm_tpu.serve.metrics import parse_prometheus
    with urllib.request.urlopen(server.url + "/metrics", timeout=30) as r:
        return parse_prometheus(r.read().decode())


def next_round(out_dir: str) -> int:
    n = 0
    for f in glob.glob(os.path.join(out_dir, "SERVE_r*.json")):
        m = re.search(r"SERVE_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Serving bench (serve/)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes + HTTP leg + assertions; prints one "
                         "JSON line, writes no artifact (CI leg)")
    ap.add_argument("--json", action="store_true",
                    help="print the record as one JSON line, no file")
    ap.add_argument("--out", default=REPO,
                    help="artifact directory (default: repo root)")
    ap.add_argument("--round", type=int, default=0,
                    help="round number (default: next free SERVE_rN)")
    ap.add_argument("--explain-frac", type=float, default=None,
                    help="fraction of open-loop arrivals that are "
                         "/explain TreeSHAP requests (default: "
                         "SERVE_EXPLAIN_FRAC or 0.1 full / 0.2 smoke; "
                         "0 disables the mixed leg)")
    args = ap.parse_args(argv)
    k = knobs(args.smoke)
    if args.explain_frac is not None:
        k["explain_frac"] = args.explain_frac

    import numpy as np

    import jax
    from lightgbm_tpu import obs
    from lightgbm_tpu.serve import PredictServer, PredictorSession

    with tempfile.TemporaryDirectory(prefix="serve_bench_") as workdir:
        if not obs.enabled():
            # a sink arms the recompile counter; the serve_* events feed
            # the digest embedded below
            obs.enable(os.path.join(workdir, "telem"))
        model_path = build_model(k, workdir)
        rng = np.random.default_rng(3)
        F = k["features"]
        Xpool = np.hstack([rng.normal(size=(4096, F - 1)),
                           rng.integers(-1, 20, size=(4096, 1)
                                        ).astype(np.float64)])
        Xpool[:, :F - 1][rng.random((4096, F - 1)) < 0.05] = np.nan

        compiles0 = obs.counter_value("jax/compiles")
        sess = PredictorSession(model_path, max_batch=k["max_batch"],
                                max_wait_ms=2.0)
        sess.warmup()
        if k["explain_frac"] > 0 and sess.explain_enabled:
            # pre-compile the explain bucket family too, so the mixed
            # leg's explain_p99 measures serving, not XLA compilation
            sess.warmup_explain()
        record = {
            "kind": "serve", "t": round(time.time(), 1),
            "backend": jax.default_backend(),
            "rows": k["rows"], "trees": sess.num_trees,
            "num_class": sess.num_tpi, "max_batch": sess.max_batch,
            "warm_compiles": int(obs.counter_value("jax/compiles")
                                 - compiles0),
        }
        record["closed"] = closed_loop(sess, Xpool, k)
        record["open"] = open_loop(sess, Xpool, k)
        server = PredictServer(sess).start()
        if args.smoke:
            record["http"] = http_smoke(server, Xpool, k)
        # end-of-run /metrics scrape: the SERVER-SIDE latency view rides
        # the artifact next to the client-observed one, so
        # bench_history.py can flag client-vs-server skew (network/queue
        # pathology the session never sees).  Best-effort: a transient
        # scrape failure must not void a completed bench round (same
        # contract as tpu_window.py's export_serve_trace)
        try:
            record["metrics_snapshot"] = scrape_metrics(server)
        except Exception as exc:  # noqa: BLE001 — capture must survive
            record["metrics_snapshot"] = None
            record["metrics_scrape_error"] = f"{type(exc).__name__}: {exc}"
        server.stop()
        st = sess.stats()
        record["server"] = {
            "p50_ms": st["p50_ms"], "p99_ms": st["p99_ms"],
            "slo_p99_ms": st["slo_p99_ms"], "slo_burn": st["slo_burn"],
            "uptime_s": st["uptime_s"],
            "compile_count": st["compile_count"],
        }
        flight_out = os.environ.get("SERVE_FLIGHT_OUT", "")
        if flight_out:
            # tpu_window.py's bench_serve leg: one good window leaves a
            # flight artifact beside the trace/telemetry captures
            with open(flight_out, "w") as fh:
                json.dump({"kind": "flight", "reason": "bench_serve",
                           "t": round(time.time(), 1),
                           "events": obs.flight_snapshot()},
                          fh, indent=1, default=str)
            record["flight_out"] = flight_out
        if st.get("explain_armed"):
            # the server-side TreeSHAP view beside the client-observed
            # explain_p99 (bench_history.py trends both)
            record["explain"] = {
                f: st.get(f) for f in
                ("explain_requests", "explain_ok", "explain_batches",
                 "explain_rows", "explain_occupancy", "explain_p50_ms",
                 "explain_p99_ms", "explain_buckets",
                 "explain_max_batch")}
            record["explain"]["compile_bound"] = int(
                math.ceil(math.log2(max(sess.explain_max_batch, 2)))) + 1
        sess.close()
        record["compiles"] = int(obs.counter_value("jax/compiles")
                                 - compiles0)
        # two independent pow2 bucket families, each with its own
        # compile budget: predict's and (when armed) explain's
        record["compile_bound"] = int(
            math.ceil(math.log2(max(sess.max_batch, 2)))) + 1
        if "explain" in record:
            record["compile_bound"] += record["explain"]["compile_bound"]
        record["occupancy"] = st["occupancy"]
        record["buckets"] = st["buckets"]
        record["degraded"] = st["degraded"]
        record["batcher_alive"] = sess._batcher._thread.is_alive()
        if _env("SERVE_SWAP", int, 1):
            # multi-model Poisson mix + hot-swap mid-run: its own
            # registry/fleet, run AFTER the single-session compile
            # accounting above (the fleet's packs/warmups must not
            # count against the session's pow2 bucket budget)
            record["swap"] = swap_leg(k, workdir, model_path)
        if _env("SERVE_COLDSTART", int, 1):
            # fresh-subprocess cold boot, AOT store on vs off — also
            # after the compile accounting (the warm-up export pays
            # compiles in THIS process on the store's behalf)
            record["coldstart"] = coldstart_leg(k, workdir, model_path,
                                                Xpool)
        if _env("SERVE_ARENA", int, 1):
            # multi-tenant Zipf mix: per-model sessions vs one arena
            record["arena"] = arena_leg(k, workdir, Xpool)

    if args.smoke:
        checks = {
            "p99_recorded": record["closed"]["p99_ms"] is not None,
            "http_ok": bool(record["http"]["requests"])
            and not record["http"]["errors"],
            "health_ok": record["http"]["health"].get("status")
            in ("ok", "degraded"),
            # /health must carry the load-balancer signals (ISSUE 6)
            "health_signals": all(
                f in record["http"]["health"]
                for f in ("queue_rows", "uptime_s", "compile_count",
                          "slo_burn")),
            # /metrics + /debug/flight answered while the POST storm ran
            "metrics_under_load": record["http"]["metrics_polls"] >= 1
            and not record["http"]["poll_errors"],
            "flight_under_load": record["http"]["flight_polls"] >= 1,
            "server_p99_recorded":
                record["server"]["p99_ms"] is not None,
            "compiles_bounded":
                record["compiles"] <= record["compile_bound"],
            "no_errors": record["closed"]["errors"] == 0
            and record["open"]["failures"] == 0,
            "not_degraded": not record["degraded"],
            "clean_shutdown": not record["batcher_alive"],
        }
        if record["open"].get("explain_frac", 0) > 0:
            x = record.get("explain") or {}
            checks.update({
                # the mixed leg actually exercised the explain queue…
                "explain_served":
                    record["open"].get("explain_completed", 0) > 0,
                "explain_no_failures":
                    record["open"].get("explain_failures", 0) == 0,
                # …within its own pow2 bucket family's compile budget
                "explain_buckets_bounded":
                    len(x.get("explain_buckets") or [])
                    <= x.get("compile_bound", 0),
            })
        if record.get("swap"):
            sw = record["swap"]
            checks.update({
                # the hot swap completed and cost zero requests: every
                # Poisson arrival admitted before/during/after the flip
                # resolved successfully (the zero-in-flight-loss
                # contract), no rollback fired, and the blip p99 was
                # measurable
                "swap_ok": bool(sw.get("swap_ok")),
                "swap_no_request_loss": sw.get("failures") == 0
                and sw.get("completed", 0) > 0,
                "swap_no_rollback": sw.get("rollbacks") == 0,
                "swap_steady_p99_recorded":
                    sw.get("steady_p99_ms") is not None,
            })
        if record.get("coldstart"):
            cs = record["coldstart"]
            checks.update({
                # the tentpole contract: a cold process with a warmed
                # store serves the whole pow2 sweep with ZERO compiles…
                "coldstart_zero_compiles": cs.get("cold_compiles") == 0,
                # …the JIT baseline actually pays them (the A/B is live)…
                "coldstart_off_pays_jit":
                    (cs.get("cold_compiles_off") or 0) >= 1,
                # …and the deserialized executables answer bit-identically
                "coldstart_bit_identical": cs.get("bit_identical") is True,
                "coldstart_measured":
                    cs.get("serve_coldstart_ms") is not None,
            })
        if record.get("arena"):
            ar = record["arena"]
            checks.update({
                "arena_parity": ar.get("parity") is True,
                "arena_no_failures": ar["solo"]["failures"] == 0
                and ar["arena"]["failures"] == 0,
                # the whole point: requests for different tenants shared
                # device dispatches (speedup itself is trended, not
                # gated — CPU smoke boxes are too noisy to pin 1.5x)
                "arena_cross_model_coalesced":
                    ar.get("cross_model_batches", 0) >= 1,
                "arena_speedup_recorded": ar.get("speedup") is not None,
            })
        record["checks"] = checks
        record["ok"] = all(checks.values())
        print(json.dumps(record))
        return 0 if record["ok"] else 1

    n = args.round or next_round(args.out)
    record["n"] = n
    if args.json:
        print(json.dumps(record))
        return 0
    path = os.path.join(args.out, f"SERVE_r{n:02d}.json")
    with open(path, "w") as fh:
        json.dump(record, fh, indent=1)
    print(f"# wrote {path}")
    print(json.dumps({"n": n,
                      "closed_rows_per_s": record["closed"]["rows_per_s"],
                      "closed_p99_ms": record["closed"]["p99_ms"],
                      "open_p99_ms": record["open"]["p99_ms"],
                      "explain_p99_ms":
                          record["open"].get("explain_p99_ms"),
                      "server_p99_ms": record["server"]["p99_ms"],
                      "slo_burn": record["server"]["slo_burn"],
                      "occupancy": record["occupancy"],
                      "swap_blip_p99_ms":
                          (record.get("swap") or {}).get(
                              "swap_blip_p99_ms"),
                      "rollbacks":
                          (record.get("swap") or {}).get("rollbacks"),
                      "serve_coldstart_ms":
                          (record.get("coldstart") or {}).get(
                              "serve_coldstart_ms"),
                      "cold_compiles":
                          (record.get("coldstart") or {}).get(
                              "cold_compiles"),
                      "arena_speedup":
                          (record.get("arena") or {}).get("speedup"),
                      "compiles": record["compiles"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
