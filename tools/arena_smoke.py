"""CPU-smokeable arena + AOT tier: the zero-cold-start contracts in CI.

``tools/run_suite.py`` runs this as the ``arena`` tier every round, so
regressions in the ISSUE 19 plane (serve/aot.py + serve/arena.py) are
caught on CPU without a TPU window:

- **AOT round-trip**: a warmed session exports every pow2 bucket
  executable; a second session in the same process deserializes them
  and serves the FULL sweep with a compile-count delta of exactly zero
  (the second session's jit function is fresh, so any non-AOT dispatch
  would compile) and bit-identical output.
- **arena parity**: binary-with-NaN, multiclass, and categorical tenant
  models packed into one ``ForestArena`` predict bit-identically to
  dedicated per-model ``PredictorSession``s — converted AND raw score.
- **cross-model coalescing**: interleaved small submits for different
  tenants land in shared device batches (``cross_model_batches`` > 0).
- **eviction / re-admission**: an impossible byte budget forces LRU
  eviction; the evicted tenant's next request transparently re-admits
  it with bit-identical output.

    python tools/arena_smoke.py --json      # one JSON verdict line
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _train(params, X, y, rounds=6, cat=None):
    import lightgbm_tpu as lgb
    p = dict({"verbose": -1, "num_leaves": 7, "min_data_in_leaf": 5},
             **params)
    ds = lgb.Dataset(X, label=y, params=p,
                     **({"categorical_feature": cat} if cat else {}))
    return lgb.train(p, ds, num_boost_round=rounds)


def build_fixtures():
    """Three small tenants covering the binning surface: NaN-heavy
    binary, multiclass, and categorical."""
    rng = np.random.default_rng(7)
    Xb = rng.normal(size=(400, 5))
    Xb[rng.random(Xb.shape) < 0.08] = np.nan
    yb = (np.nan_to_num(Xb[:, 0]) > 0).astype(np.float64)
    b_bin = _train({"objective": "binary"}, Xb, yb)

    Xm = rng.normal(size=(400, 4))
    ym = (np.digitize(Xm[:, 0], [-0.5, 0.5])).astype(np.float64)
    b_mc = _train({"objective": "multiclass", "num_class": 3}, Xm, ym)

    Xc = np.hstack([rng.normal(size=(400, 3)),
                    rng.integers(0, 12, size=(400, 1)).astype(np.float64)])
    yc = ((Xc[:, 0] + 0.3 * (Xc[:, 3] % 4)) > 0).astype(np.float64)
    b_cat = _train({"objective": "binary"}, Xc, yc, cat=[3])
    return (b_bin, Xb), (b_mc, Xm), (b_cat, Xc)


def aot_roundtrip(fixtures):
    """Export -> deserialize -> serve with compile count pinned at 0."""
    from lightgbm_tpu import obs
    from lightgbm_tpu.serve import PredictorSession
    (b_bin, Xb) = fixtures[0]
    max_batch = 64
    with tempfile.TemporaryDirectory(prefix="arena_smoke_aot_") as d:
        cfg = {"verbose": -1, "tpu_serve_aot_dir": d}
        warm = PredictorSession(b_bin, max_batch=max_batch,
                                max_wait_ms=1.0, config=cfg)
        warm.warmup()
        want = {n: warm.predict(Xb[:n]) for n in (1, 2, 4, 8, 16, 32, 64)}
        saved = (warm.stats().get("aot") or {}).get("saved", 0)
        warm.close()
        check("aot.exported", saved >= 1, f"saved={saved}")

        obs.install_recompile_hook()
        c0 = obs.compile_count()
        cold = PredictorSession(b_bin, max_batch=max_batch,
                                max_wait_ms=1.0, config=cfg)
        got = {n: cold.predict(Xb[:n]) for n in (1, 2, 4, 8, 16, 32, 64)}
        delta = obs.compile_count() - c0
        st = cold.stats().get("aot") or {}
        cold.close()
        # a fresh session means a fresh jit callable — a single non-AOT
        # dispatch anywhere in the sweep would show up as a compile
        check("aot.roundtrip_zero_compiles",
              delta == 0 and len(st.get("buckets") or []) >= 7,
              f"{delta} compiles, buckets={st.get('buckets')}")
        check("aot.roundtrip_bit_identical",
              all(np.array_equal(want[n], got[n]) for n in want))


def arena_parity(fixtures):
    from lightgbm_tpu.serve import ForestArena, PredictorSession
    arena = ForestArena(max_batch=64, max_wait_ms=1.0)
    names = ("bin", "mc", "cat")
    try:
        for name, (bst, _) in zip(names, fixtures):
            arena.admit(name, bst)
        for name, (bst, X) in zip(names, fixtures):
            probe = X[:48]
            with PredictorSession(bst, max_batch=64,
                                  max_wait_ms=1.0) as solo:
                check(f"arena.parity_{name}",
                      np.array_equal(arena.predict(probe, model=name),
                                     solo.predict(probe))
                      and np.array_equal(
                          arena.predict(probe, model=name,
                                        raw_score=True),
                          solo.predict(probe, raw_score=True)))
        # cross-model coalescing: interleaved async submits for all
        # three tenants inside one batching window share dispatches
        tickets = []
        for r in range(8):
            for name, (_, X) in zip(names, fixtures):
                tickets.append(
                    (name, arena.submit(X[r * 2:r * 2 + 2], model=name)))
        for _, t in tickets:
            arena.result(t, timeout=60.0)
        st = arena.stats()
        check("arena.cross_model_coalesced",
              st["cross_model_batches"] >= 1
              and st["batches"] < len(tickets), st)
    finally:
        arena.close()


def eviction_readmission(fixtures):
    from lightgbm_tpu.serve import ForestArena, PredictorSession
    (b_bin, Xb), (b_mc, _), _ = fixtures
    arena = ForestArena(budget_bytes=1, max_batch=64, max_wait_ms=1.0)
    try:
        arena.admit("a", b_bin)
        arena.admit("b", b_mc)      # 1-byte budget: LRU 'a' must go
        st = arena.stats()
        check("arena.budget_evicts",
              st["evictions"] >= 1 and st["resident"] == 1, st)
        out = arena.predict(Xb[:32], model="a")   # re-admits 'a'
        st2 = arena.stats()
        with PredictorSession(b_bin, max_batch=64,
                              max_wait_ms=1.0) as solo:
            check("arena.readmit_bit_identical",
                  st2["readmissions"] >= 1
                  and np.array_equal(out, solo.predict(Xb[:32])), st2)
    finally:
        arena.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Arena + AOT smoke (serve/aot.py, serve/arena.py)")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    args = ap.parse_args(argv)

    t0 = time.time()
    fixtures = build_fixtures()
    aot_roundtrip(fixtures)
    arena_parity(fixtures)
    eviction_readmission(fixtures)

    record = {
        "kind": "arena_smoke",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
    }
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
