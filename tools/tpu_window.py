"""Self-arming TPU measurement watcher — seize the lease window.

The project's open risk is one unmeasured number: rounds 4-5 ended with
zero TPU datapoints because the chip lease never overlapped a human
being ready to run the ROOFLINE.md first-window checklist.  This tool
removes the human from the loop: it probes backend liveness in a
SUBPROCESS on an interval (in-process ``jax.devices()`` can hang ~30 min
when the axon lease wedges — same reasoning as ``bench.py _tpu_alive``),
and the moment ``jax.default_backend() != 'cpu'`` it runs the whole
capture checklist with health monitoring enabled:

1. ``python bench.py`` — the clean throughput number (async dispatch
   intact; health monitor + telemetry certify it carried no NaNs);
2. ``python bench.py`` under ``LGBM_TPU_PROFILE=1`` — per-kernel
   roofline fractions + the HBM census;
3. ``python bench.py`` with ``BENCH_MAXBIN=63`` — the 4x-denser MXU
   packing variant the roofline model predicts wins;
3b. ``python bench.py`` with ``BENCH_FUSED=0`` — the unfused-sibling
   A/B (ISSUE 8): same trees, separate XLA subtraction pass, so the
   delta vs leg 1 is the in-kernel fusion win, end to end;
3c. ``python bench.py`` with ``BENCH_QUANT=int16`` — the quantized-
   accumulation A/B (ISSUE 11): same problem, quantization-only delta,
   so one window prices the int16 grad/hess lanes against leg 1;
3d. ``python bench.py`` with ``BENCH_FUSED_GRAD=0`` — the fused-
   gradient A/B twin: bit-identical trees, the delta is the per-
   iteration [N] g/h HBM round-trip the fused pass deletes;
3e. ``python bench.py`` with ``BENCH_TASK=rank`` — the dedicated
   MSLR-shaped lambdarank leg (ISSUE 13: device lambda pair pass +
   device NDCG eval), written as ``BENCH_rank_manual_r{N}.json`` so
   one window finally yields a clean ``rank_vs_baseline`` trajectory
   point beside the HIGGS one;
4. ``tools/prof_kernels.py`` (``PROF_JSON=1``) — the leg decomposition,
   including the wave-partition legs (batched one-pass split apply vs
   the sequential per-split oracle, against ``partition_cost``) and the
   packed/fused kernel-layout legs (triple vs lane-pair vs fused);
5. a ``jax.profiler`` trace capture of a short training run, taken
   with telemetry armed so the ``lgbm/*`` scope annotations land in
   the artifacts; the window then parses its OWN capture through the
   measured-roofline plane (``obs/xprof.py``, ISSUE 18) and embeds
   the per-kernel ``kernel_measured`` table (achieved ms vs cost-model
   ms, roofline fraction, boundedness) into ``BENCH_manual_r{N}`` —
   a captured-but-unparseable trace is classified into ``triage`` as
   ``unparseable-trace`` instead of silently passing the file-count
   check;
6. ``tools/bench_serve.py --json`` — the serving engine's closed-loop +
   Poisson open-loop numbers on the live backend, written as
   ``SERVE_manual_r{N}.json`` (bench_history.py trends it alongside
   the ``SERVE_r*.json`` CI rounds).  The leg runs with
   ``LGBM_TPU_TRACE=1`` and a flight capture, so one good window also
   yields a Perfetto-loadable ``serve_trace.json`` (request span trees)
   and a ``FLIGHT_serve.json`` flight record in the artifacts dir.
   Since ISSUE 10 the leg also exercises ONE registry hot-swap under
   its Poisson mix (bench_serve's swap leg), and the window record
   stamps ``swap_blip_p99_ms`` / ``rollbacks`` at top level — a real
   on-TPU datapoint for "what does a model push cost the p99";
7. ``tools/bench_serve.py --json --explain-frac 0.5`` — the
   explanation-serving leg (ISSUE 9): half the open-loop Poisson
   arrivals are ``/explain`` TreeSHAP requests, so the window captures
   ``explain_p99`` under real mixed contention on the live backend,
   written as ``SERVE_explain_manual_r{N}.json``;
8. ``tools/ingest_bench.py --json`` — the streaming-ingestion leg
   (ISSUE 14): synthetic-stream two-pass construction throughput
   (``ingest_rows_per_s``) + the bounded-memory proof on the window's
   host, written as ``INGEST_manual_r{N}.json`` (pass the file to
   ``bench_history.py`` explicitly to fold it into the trend beside
   the auto-globbed CI ``INGEST_r*`` rounds, like ``SERVE_manual``);
9. ``tools/fleet_smoke.py --json`` — the elastic-fleet leg (ISSUE 20):
   3-process gang launch over the host-TCP transport, bit-exactness vs
   the single-process oracle on plain/bagging/ranking, and the
   kill-one-rank recovery, written as ``FLEET_manual_r{N}.json`` (same
   pass-explicitly convention as the other manual records).

Artifacts (``--out``, default repo root):

- ``BENCH_manual_r{N}.json`` — one bench_history.py-compatible record:
  the clean bench's parsed JSON line (which now embeds
  ``health_checks``/``health_failures``) plus every leg's rc/seconds/
  parsed output and the merged health summary.  Since ISSUE 17 the
  headline leg runs with the train-side metrics exporter armed
  (``LGBM_TPU_TRAIN_METRICS``) and a mid-leg scraper embeds the live
  ``/progress`` snapshot + measured-vs-model ``reconciliation`` table
  at top level, and a ``triage`` block classifies every non-clean leg
  (``timeout`` / ``backend-wedge`` / ``cpu-fallback`` / ``failure``)
  so the record says WHY a window yielded no clean point;
- ``HEALTH_manual_r{N}.json`` — the health/fingerprint/divergence digest
  per leg + event-schema validation verdict;
- ``tpu_window_r{N}/`` — per-leg telemetry dirs + the profiler trace.

``--dry-run`` forces the CPU backend at smoke sizes and skips the
probe gate, so the ENTIRE pipeline is testable in this container (CI
runs it; on a real window only the sizes differ).  ``--once`` probes a
single time instead of looping; ``--max-wait`` bounds the loop.

Run: PYTHONPATH=/root/repo:/root/.axon_site python tools/tpu_window.py
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# smoke sizes for --dry-run: every leg finishes in O(compile time) on the
# 1-CPU container while exercising the exact artifact pipeline
_DRY_BENCH_ENV = {
    "JAX_PLATFORMS": "cpu",
    "BENCH_FORCE_CPU": "1", "BENCH_CPU_ROWS": "20000", "BENCH_ITERS": "3",
    "BENCH_LEAVES": "31", "BENCH_RANK_ROWS": "5000", "BENCH_RANK_ITERS": "2",
}
_DRY_PROF_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PROF_INTERPRET": "1", "PROF_ROWS": "4096", "PROF_FEATURES": "6",
    "PROF_LEAVES": "7", "PROF_MAXBIN": "63", "PROF_REPEAT": "1",
    "PROF_LEGS": "kernel,kernelpacked,kernelfused,kernelint16,"
                 "kernelint8,fusedgrad,gathers,partition",
}
_DRY_SERVE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "SERVE_ROWS": "2000", "SERVE_TREES": "20", "SERVE_FEATURES": "8",
    "SERVE_MAX_BATCH": "128", "SERVE_CLIENTS": "2",
    "SERVE_DURATION_S": "1.5", "SERVE_RATE": "40",
}
# ingest_bench's built-in defaults ARE smoke-sized (120k rows, ~2s);
# shrinking them further would starve the bounded-memory check of the
# raw-matrix headroom it measures against, so the dry leg only pins
# the backend
_DRY_INGEST_ENV = {"JAX_PLATFORMS": "cpu"}

_TRACE_CODE = """
import sys
import numpy as np
import jax
import lightgbm_tpu as lgb
rows, trace_dir = int(sys.argv[1]), sys.argv[2]
rng = np.random.default_rng(0)
X = rng.normal(size=(rows, 12))
y = (X[:, 0] + 0.3 * X[:, 1] > 0).astype(np.float64)
p = {"objective": "binary", "num_leaves": 31, "min_data_in_leaf": 5,
     "verbose": -1}
ds = lgb.Dataset(X, label=y, params=p)
bst = lgb.Booster(params=p, train_set=ds)
bst.update()  # compile outside the trace
with jax.profiler.trace(trace_dir):
    for _ in range(2):
        bst.update()
    jax.block_until_ready(bst._gbdt._train_score)
print("TRACE_OK")
"""


def probe_backend(timeout_s: int = 120, py: str = sys.executable,
                  runner=subprocess.run):
    """(armed, backend_name): True when a non-CPU backend answered within
    the timeout.  Subprocess-isolated so a wedged lease cannot hang the
    watcher itself."""
    code = ("import jax, sys\n"
            "b = jax.default_backend()\n"
            "print(b)\n"
            "sys.exit(0 if b != 'cpu' else 2)\n")
    try:
        r = runner([py, "-c", code], timeout=timeout_s,
                   capture_output=True, text=True)
    except (subprocess.TimeoutExpired, OSError):
        return False, "timeout"
    out = (r.stdout or "").strip().splitlines()
    return r.returncode == 0, (out[-1] if out else "")


def next_round(out_dir: str) -> int:
    n = 0
    for f in glob.glob(os.path.join(out_dir, "BENCH_manual_r*.json")):
        m = re.search(r"BENCH_manual_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def _free_port() -> int:
    """A currently-free TCP port for the bench leg's train board — the
    subprocess needs a KNOWN port (ephemeral 0 would hide it from the
    mid-leg scraper).  Tiny bind race, acceptable for a manual tool."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def checklist_legs(art_dir: str, dry_run: bool, py: str = sys.executable):
    """The ROOFLINE.md first-window checklist as (name, argv, env) legs.
    Every leg runs with health monitoring on and its own telemetry dir,
    so the capture certifies itself."""
    bench = os.path.join(REPO, "bench.py")
    prof = os.path.join(REPO, "tools", "prof_kernels.py")
    serve = os.path.join(REPO, "tools", "bench_serve.py")
    ingest = os.path.join(REPO, "tools", "ingest_bench.py")
    fleet = os.path.join(REPO, "tools", "fleet_smoke.py")
    trace_dir = os.path.join(art_dir, "trace")

    def env_for(tag, extra=None, dry_env=None):
        env = {"LGBM_TPU_HEALTH": "monitor",
               "LGBM_TPU_TELEMETRY": os.path.join(art_dir, f"telem_{tag}"),
               # every leg carries a flight ring dumping into the
               # artifacts dir, so a wedged leg leaves its own
               # post-mortem beside the bench numbers (ISSUE 7)
               "LGBM_TPU_FLIGHT": "256",
               "LGBM_TPU_FLIGHT_DIR": art_dir}
        if dry_run:
            env.update(dry_env if dry_env is not None else _DRY_BENCH_ENV)
        if extra:
            env.update(extra)
        return env

    trace_rows = "2000" if dry_run else "50000"
    # the trace leg runs with telemetry ARMED: core.phase only stamps
    # the lgbm/* TraceAnnotations the measured-roofline parser
    # attributes by when a sink is live (obs/core._trace_annotation),
    # so a bare capture would parse to zero attributed kernels.
    # LGBM_TPU_XPROF=0 disarms the in-process capture window — the
    # leg's outer jax.profiler.trace IS the capture here, and a nested
    # profiler session would abort it.
    trace_env = env_for("trace", {"LGBM_TPU_XPROF": "0"},
                        dry_env={"JAX_PLATFORMS": "cpu"})
    # the headline leg runs with the train-side metrics exporter armed
    # (ISSUE 17): the window scrapes /metrics + /progress MID-LEG and
    # embeds the live measured-vs-model reconciliation table into
    # BENCH_manual_rN — proof the introspection plane works on the real
    # backend, not just in the CPU smoke
    board_port = _free_port()
    return [
        {"name": "bench", "argv": [py, bench],
         "env": env_for("bench",
                        {"LGBM_TPU_TRAIN_METRICS": str(board_port)}),
         "scrape_port": board_port, "parse_json": True},
        {"name": "bench_profile", "argv": [py, bench],
         "env": env_for("bench_profile", {"LGBM_TPU_PROFILE": "1"}),
         "parse_json": True},
        {"name": "bench_maxbin63", "argv": [py, bench],
         "env": env_for("bench_maxbin63", {"BENCH_MAXBIN": "63"}),
         "parse_json": True},
        # the fused-sibling A/B: one window measures the in-kernel
        # subtraction win end to end (ISSUE 8) — bench_history reads the
        # fused_sibling stamp so the legs trend separately
        {"name": "bench_unfused", "argv": [py, bench],
         "env": env_for("bench_unfused", {"BENCH_FUSED": "0"}),
         "parse_json": True},
        # the quantized-accumulation A/B (ISSUE 11): same problem,
        # quantization-only delta — bench_history reads the hist_mode
        # stamp so the legs trend separately and a silent downgrade to
        # f32 is flagged like a fused_sibling flip
        {"name": "bench_quant", "argv": [py, bench],
         "env": env_for("bench_quant", {"BENCH_QUANT": "int16"}),
         "parse_json": True},
        # the fused-gradient A/B twin: bit-identical trees, the delta
        # is the per-iteration [N] g/h HBM round-trip
        {"name": "bench_nofusedgrad", "argv": [py, bench],
         "env": env_for("bench_nofusedgrad", {"BENCH_FUSED_GRAD": "0"}),
         "parse_json": True},
        # the ranking-plane leg (ISSUE 13): a dedicated BENCH_TASK=rank
        # run at full rank size (the headline's embedded rank leg runs
        # at reduced BENCH_RANK_ROWS), written as BENCH_rank_manual_rN
        # — the first clean window prices the device lambda/NDCG plane
        # and bench_history trends its rank_vs_baseline point
        {"name": "bench_rank", "argv": [py, bench],
         "env": env_for("bench_rank", {"BENCH_TASK": "rank",
                                       "BENCH_CPU_ROWS": "8000"}),
         "parse_json": True},
        {"name": "prof_kernels", "argv": [py, prof],
         "env": env_for("prof_kernels", {"PROF_JSON": "1"},
                        dry_env=_DRY_PROF_ENV),
         "parse_json": True},
        {"name": "bench_serve", "argv": [py, serve, "--json"],
         "env": env_for("bench_serve",
                        # trace + flight capture: one good window leaves
                        # a Perfetto-exportable span stream AND a flight
                        # record beside the bench numbers (ISSUE 6).
                        # SERVE_COLDSTART pinned on (ISSUE 19): the
                        # window stamps serve_coldstart_ms — a real
                        # on-TPU exec-to-request-#1 number with the AOT
                        # store armed — beside the swap blip
                        {"LGBM_TPU_TRACE": "1",
                         "SERVE_COLDSTART": "1",
                         "SERVE_FLIGHT_OUT": os.path.join(
                             art_dir, "FLIGHT_serve.json")},
                        dry_env=_DRY_SERVE_ENV),
         "parse_json": True},
        # explanation-serving leg (ISSUE 9): an explain-heavy mix so the
        # window yields a TreeSHAP p99 under contention, not an
        # idle-path number — its own telemetry dir keeps the span
        # streams separable
        {"name": "bench_explain",
         "argv": [py, serve, "--json", "--explain-frac", "0.5"],
         # the hot-swap / cold-start / arena exercises belong to the
         # bench_serve leg; this one stays a pure explain-mix
         # measurement
         "env": env_for("bench_explain", {"SERVE_SWAP": "0",
                                          "SERVE_COLDSTART": "0",
                                          "SERVE_ARENA": "0"},
                        dry_env=_DRY_SERVE_ENV),
         "parse_json": True},
        # streaming-ingestion leg (ISSUE 14): the synthetic-stream
        # two-pass bench — ingest_rows_per_s + the bounded-memory proof
        # on whatever host backs this window; artifact written by the
        # window itself (INGEST_manual_rN) so the repo root stays clean
        {"name": "bench_ingest",
         "argv": [py, ingest, "--json", "--no-write"],
         "env": env_for("bench_ingest", dry_env=_DRY_INGEST_ENV),
         "parse_json": True},
        # elastic-fleet leg (ISSUE 20): a real 3-process gang launch
        # over the host-TCP transport — bit-exactness vs the
        # single-process oracle plus the kill-one-rank recovery, on
        # whatever host backs this window; artifact written by the
        # window itself (FLEET_manual_rN) so the repo root stays clean
        {"name": "bench_fleet",
         "argv": [py, fleet, "--json", "--no-write"],
         "env": env_for("bench_fleet", dry_env={"JAX_PLATFORMS": "cpu"}),
         "parse_json": True},
        {"name": "trace",
         "argv": [py, "-c", _TRACE_CODE, trace_rows, trace_dir],
         "env": trace_env, "parse_json": False},
    ], trace_dir


def _parse_json_tail(stdout: str):
    """Last parseable JSON object line of a leg's stdout (bench.py and
    PROF_JSON both print exactly one)."""
    for line in reversed((stdout or "").splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except ValueError:
                continue
    return None


def _run_one(leg, runner, timeout):
    env = {**os.environ, **leg["env"]}
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    try:
        r = runner(leg["argv"], env=env, cwd=REPO, timeout=timeout,
                   capture_output=True, text=True)
        return r.returncode, r.stdout or "", r.stderr or "", False
    except subprocess.TimeoutExpired as exc:
        # keep the partial output: how far a leg got before wedging
        # IS the diagnostic this watcher exists to capture
        def _s(b):
            return (b.decode(errors="replace")
                    if isinstance(b, bytes) else (b or ""))
        return (-1, _s(exc.stdout),
                _s(exc.stderr) + f"\n[timed out after {timeout}s]", True)
    except OSError as exc:
        return -2, "", f"{type(exc).__name__}: {exc}", False


def _scrape_board(port: int, state: dict, stop: threading.Event,
                  poll_s: float = 0.15) -> None:
    """Poller thread body: keep the LAST successful /progress +
    /metrics snapshot from a leg's train board.  Misses are normal
    (the board only exists while the subprocess trains)."""
    base = f"http://127.0.0.1:{port}"
    while not stop.is_set():
        try:
            with urllib.request.urlopen(base + "/progress",
                                        timeout=2) as resp:
                pr = json.loads(resp.read())
            state["progress"] = pr
            if pr.get("reconciliation"):
                # bench arms several boards back to back (headline +
                # embedded rank leg); keep the last snapshot that
                # carries the reconciliation table so a later tiny
                # leg's board can't blank the embed
                state["progress_recon"] = pr
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=2) as resp:
                state["metrics_text"] = resp.read().decode()
            state["scrapes"] = state.get("scrapes", 0) + 1
        except Exception:
            pass
        stop.wait(poll_s)


def _board_snapshot(state: dict):
    """Trim a scraped board state into the record's ``board`` block:
    the reconciliation table + headline progress, plus proof the
    exposition parses through the shared serve reader."""
    pr = state.get("progress_recon") or state.get("progress")
    if not pr:
        return None
    snap = {
        "scrapes": state.get("scrapes", 0),
        "iteration": pr.get("iteration"),
        "total_rounds": pr.get("total_rounds"),
        "eta_s": pr.get("eta_s"),
        "row_iters_per_s": pr.get("row_iters_per_s"),
        "vs_baseline": pr.get("vs_baseline"),
        "reconciliation": pr.get("reconciliation"),
        "stragglers": pr.get("stragglers"),
    }
    mtext = state.get("metrics_text")
    if mtext:
        try:
            from lightgbm_tpu.serve.metrics import parse_prometheus
            snap["metrics_series"] = len(parse_prometheus(mtext))
        except Exception:
            snap["metrics_series"] = None
    return snap


def leg_triage(rec: dict, dry_run: bool = False):
    """Why did this leg not yield a clean point?  ``None`` for a clean
    leg; else one of ``timeout`` (the subprocess hit the window's
    deadline), ``backend-wedge`` (transient runtime failure shape —
    robust/watchdog.py classify_text — that exhausted its retries),
    ``cpu-fallback`` (ran green but on the CPU backend, so the number
    is not a device point), ``unparseable-trace`` (the capture leg
    left artifacts the measured-roofline parser could not read —
    ISSUE 18 — so the window yielded no per-kernel truth), or
    ``failure`` (a real error: retrying would only repeat it)."""
    parsed = rec.get("parsed") or {}
    if rec.get("trace_unparseable"):
        # checked BEFORE the rc == 0 early-return: the capture
        # subprocess exits green even when its artifacts are garbage
        return "unparseable-trace"
    if rec.get("rc", 1) == 0:
        if not dry_run and parsed.get("backend") == "cpu":
            return "cpu-fallback"
        return None
    if rec.get("rc") == -1:
        return "timeout"
    if rec.get("wedge_class"):
        return "backend-wedge"
    from lightgbm_tpu.robust.watchdog import classify_text
    tail = "\n".join(rec.get("tail") or [])
    if classify_text(tail) is not None:
        return "backend-wedge"
    return "failure"


def triage_legs(results: dict, dry_run: bool = False):
    """The record's top-level ``triage`` block (ISSUE 17): per-leg
    classification of every non-clean leg so bench_history.py can say
    WHY a window produced no clean point.  ``None`` when every leg was
    clean (the block's absence IS the clean signal)."""
    legs = {name: cls for name, rec in results.items()
            for cls in [leg_triage(rec, dry_run=dry_run)] if cls}
    if not legs:
        return None
    return {"legs": legs, "classes": sorted(set(legs.values()))}


def run_legs(legs, runner=subprocess.run, timeout: int = 1800,
             wedge_retries: int = 1, backoff_s: float = 5.0):
    """Run the checklist legs; a leg that dies in a WEDGE-shaped way
    (timeout, or a transient runtime error in its output tail) is
    retried up to ``wedge_retries`` times with exponential backoff +
    seeded jitter instead of abandoning the window — the same
    classify/backoff path the in-process watchdog applies, lifted to
    the subprocess level (robust/watchdog.py classify_text).  Each
    leg's record carries ``wedge_retries``/``wedge_class`` so
    bench_history.py can distinguish recovered rounds from clean
    ones."""
    from lightgbm_tpu.robust.watchdog import backoff_delays, classify_text
    results = {}
    for leg in legs:
        t0 = time.time()
        print(f"# leg {leg['name']}: {' '.join(leg['argv'][:2])} ...",
              flush=True)
        attempts = 0
        wedge_class = None
        scrape_state, scrape_stop = None, None
        if leg.get("scrape_port"):
            # mid-leg board scrape (ISSUE 17): runs across retries too —
            # the last snapshot before a wedge is still a diagnostic
            scrape_state, scrape_stop = {}, threading.Event()
            threading.Thread(
                target=_scrape_board,
                args=(leg["scrape_port"], scrape_state, scrape_stop),
                daemon=True).start()
        delays = backoff_delays(max(wedge_retries, 0), base_s=backoff_s,
                                cap_s=8 * backoff_s)
        while True:
            rc, out, err, timed_out = _run_one(leg, runner, timeout)
            if rc == 0 or attempts >= wedge_retries:
                break
            cls = classify_text(out + "\n" + err, timed_out=timed_out)
            if cls is None:
                break  # a real failure — retrying would only repeat it
            wedge_class = cls
            delay = delays[min(attempts, len(delays) - 1)] if delays else 0
            print(f"# leg {leg['name']}: {cls} failure (rc={rc}) — "
                  f"retrying in {delay:.1f}s "
                  f"({attempts + 1}/{wedge_retries})", flush=True)
            time.sleep(delay)
            attempts += 1
        rec = {"rc": rc, "seconds": round(time.time() - t0, 1)}
        if scrape_stop is not None:
            scrape_stop.set()
            board = _board_snapshot(scrape_state)
            if board is not None:
                rec["board"] = board
        if attempts:
            rec["wedge_retries"] = attempts
            rec["wedge_class"] = wedge_class
            rec["recovered"] = rc == 0
        if leg["parse_json"]:
            rec["parsed"] = _parse_json_tail(out)
        tail = (out + ("\n" + err if err else "")).splitlines()[-8:]
        rec["tail"] = tail
        results[leg["name"]] = rec
        status = "ok" if rc == 0 else f"rc={rc}"
        if attempts:
            status += f" after {attempts} wedge retr" \
                      f"{'y' if attempts == 1 else 'ies'}"
        print(f"# leg {leg['name']}: {status} ({rec['seconds']}s)",
              flush=True)
    return results


def collect_health(art_dir: str) -> dict:
    """Merge every leg's telemetry dir into per-leg health digests +
    schema validation (obs/report.py — imported lazily so the module
    stays light for the probe loop)."""
    from lightgbm_tpu.obs.report import (health_summary, load_events,
                                         validate_events)
    out = {"legs": {}, "problems": [], "events_ok": True}
    for d in sorted(glob.glob(os.path.join(art_dir, "telem_*"))):
        tag = os.path.basename(d)[len("telem_"):]
        events = load_events(d)
        problems = validate_events(events)
        hs = health_summary(events)
        n_iter = sum(1 for e in events if e.get("event") == "iteration")
        out["legs"][tag] = {"events": len(events), "iterations": n_iter,
                            "health": hs, "schema_problems": len(problems)}
        out["problems"].extend(f"{tag}: {p}" for p in problems[:10])
        if problems:
            out["events_ok"] = False
    fails = sum((leg.get("health") or {}).get("failures", 0)
                for leg in out["legs"].values())
    divs = sum((leg.get("health") or {}).get("divergence_failures", 0)
               for leg in out["legs"].values())
    out["failures"] = fails
    out["divergence_failures"] = divs
    out["verdict"] = ("DIVERGED" if divs else
                      "FAILED" if fails else "healthy")
    return out


def export_serve_trace(art_dir: str):
    """Post-process the bench_serve leg's telemetry into a Perfetto
    trace file (the leg ran with LGBM_TPU_TRACE=1, so its JSONL carries
    the span stream).  Best-effort: a missing/empty stream returns None
    rather than failing the capture."""
    telem = os.path.join(art_dir, "telem_bench_serve")
    if not os.path.isdir(telem):
        return None, 0
    try:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_export
        from lightgbm_tpu.obs.report import load_events
        doc = trace_export.events_to_chrome(load_events(telem))
        if not doc["traceEvents"]:
            return None, 0
        path = os.path.join(art_dir, "serve_trace.json")
        with open(path, "w") as fh:
            json.dump(doc, fh)
        return path, len(doc["traceEvents"])
    except Exception as exc:  # noqa: BLE001 — capture must survive
        print(f"# serve trace export failed: {exc}", file=sys.stderr)
        return None, 0


def ingest_trace(trace_dir: str, dry_run: bool):
    """Parse the trace leg's OWN capture through the measured-roofline
    plane (obs/xprof.py, ISSUE 18) and join it against the analytic
    cost models at the leg's training shape.

    Returns ``(rows, summary)`` — the per-kernel ``kernel_measured``
    table plus a parse summary.  The parser itself never raises on bad
    artifacts (truncated gzip, corrupt json → ``errors`` entries), so
    a captured-but-unparseable trace surfaces as ``parsed == 0`` with
    the per-file failures listed, not as an exception."""
    from lightgbm_tpu.obs import xprof
    parsed = xprof.parse_trace_dir(trace_dir)
    attrib = xprof.attribute(parsed)
    # _TRACE_CODE's shape: rows x 12 features, 31 leaves, default bins,
    # 2 traced updates
    context = {"rows": 2000 if dry_run else 50000, "features": 12,
               "leaves": 31, "bins": 255, "iters": 2}
    rows = xprof.measured_rooflines(attrib, context)
    lgbm = [r for r in rows if r["kernel"].startswith("lgbm/")
            and r.get("measured_ms", 0) > 0]
    summary = {
        "files": attrib["files"],
        "parsed": attrib["parsed"],
        "errors": attrib["errors"][:5],
        "window_ms": attrib["window_ms"],
        "kernels_attributed": len(lgbm),
    }
    return rows, summary


def run_checklist(out_dir: str, n: int, dry_run: bool,
                  runner=subprocess.run, timeout: int = 1800,
                  backend: str = "", only=None,
                  wedge_retries: int = 1) -> dict:
    art_dir = os.path.join(out_dir, f"tpu_window_r{n:02d}")
    os.makedirs(art_dir, exist_ok=True)
    legs, trace_dir = checklist_legs(art_dir, dry_run)
    if only:
        legs = [leg for leg in legs if leg["name"] in only]
    results = run_legs(legs, runner=runner, timeout=timeout,
                       wedge_retries=wedge_retries)
    health = collect_health(art_dir)
    trace_n_files = sum(len(fs) for _, _, fs in os.walk(trace_dir))
    # parse the trace leg's own capture (ISSUE 18): the per-kernel
    # measured table rides in BENCH_manual_rN, and a captured-but-
    # unparseable trace becomes a triage class instead of silently
    # passing the trace_files > 0 check
    kernel_measured, trace_parse = [], None
    trace_rec = results.get("trace")
    if trace_rec is not None:
        try:
            kernel_measured, trace_parse = ingest_trace(trace_dir,
                                                        dry_run)
        except Exception as exc:  # noqa: BLE001 — record must survive
            trace_parse = {"files": trace_n_files, "parsed": 0,
                           "errors": [f"{type(exc).__name__}: {exc}"],
                           "window_ms": 0.0, "kernels_attributed": 0}
        trace_rec["trace_parse"] = trace_parse
        if trace_n_files > 0 and not trace_parse.get("parsed"):
            trace_rec["trace_unparseable"] = True
    bench_parsed = (results.get("bench") or {}).get("parsed")
    record = {
        "n": n,
        "kind": "manual_window",
        "t": round(time.time(), 1),
        "dry_run": dry_run,
        "backend_probe": backend,
        "cmd": "python tools/tpu_window.py"
               + (" --dry-run" if dry_run else ""),
        "rc": 0 if all(r["rc"] == 0 for r in results.values()) else 1,
        "parsed": bench_parsed,
        "legs": results,
        # total wedge retries across RECOVERED legs: >0 marks a
        # recovered round — bench_history.py flags it so a number that
        # needed retries is never quoted as a clean datapoint.  Legs
        # that retried and STILL failed leave rc!=0 on the record; their
        # attempts must not dress the round up as recovered
        "wedge_retries": sum(r.get("wedge_retries", 0)
                             for r in results.values()
                             if r.get("recovered")),
        "health": health,
        # live-introspection embed (ISSUE 17): the mid-leg board scrape
        # of the headline bench — its measured-vs-model reconciliation
        # table rides in the manual record so a TPU window prices the
        # cost models against real device walls
        "board": (results.get("bench") or {}).get("board"),
        "reconciliation": ((results.get("bench") or {}).get("board")
                           or {}).get("reconciliation"),
        # wedge triage (ISSUE 17): why each non-clean leg failed —
        # absent when the window was clean
        "triage": triage_legs(results, dry_run=dry_run),
        "trace_dir": os.path.relpath(trace_dir, out_dir),
        "trace_files": trace_n_files,
        # the measured-roofline embed (ISSUE 18): per-kernel achieved
        # ms joined against the analytic cost models, straight from the
        # trace leg's own capture — bench_history.py trends the
        # roofline fractions from these rows
        "kernel_measured": kernel_measured,
        "trace_parse": trace_parse,
        "artifacts_dir": os.path.relpath(art_dir, out_dir),
    }
    bench_path = os.path.join(out_dir, f"BENCH_manual_r{n:02d}.json")
    with open(bench_path, "w") as fh:
        json.dump(record, fh, indent=1)
    health_path = os.path.join(out_dir, f"HEALTH_manual_r{n:02d}.json")
    with open(health_path, "w") as fh:
        json.dump(health, fh, indent=1)
    print(f"# wrote {bench_path}")
    print(f"# wrote {health_path}")
    rank_parsed = (results.get("bench_rank") or {}).get("parsed")
    if rank_parsed:
        # the dedicated rank record: bench.py's BENCH_TASK=rank line
        # verbatim (value/vs_baseline + the hist_mode/fused_grad
        # stamps) — the BENCH_r* glob in bench_history.py picks
        # "BENCH_rank_manual_r*" up as its own context, so one good
        # window leaves a trendable rank_vs_baseline point
        rank_parsed = dict(rank_parsed, n=n, dry_run=dry_run)
        rank_path = os.path.join(out_dir, f"BENCH_rank_manual_r{n:02d}.json")
        with open(rank_path, "w") as fh:
            json.dump(rank_parsed, fh, indent=1)
        record["rank_path"] = rank_path
        print(f"# wrote {rank_path}")
    serve_parsed = (results.get("bench_serve") or {}).get("parsed")
    if serve_parsed:
        serve_parsed = dict(serve_parsed, n=n, dry_run=dry_run)
        # the leg's hot-swap exercise (ISSUE 10): stamp the blip p99 and
        # rollback count at top level so one window leaves a trendable
        # swap datapoint even if the embedded record shape changes
        sw = serve_parsed.get("swap") or {}
        serve_parsed["swap_blip_p99_ms"] = sw.get("swap_blip_p99_ms")
        serve_parsed["swap_steady_p99_ms"] = sw.get("steady_p99_ms")
        serve_parsed["rollbacks"] = sw.get("rollbacks")
        # the zero-cold-start + arena legs (ISSUE 19): stamp the boot
        # and throughput-ratio numbers at top level too, so one window
        # leaves trendable cold-start datapoints on the live backend
        cs = serve_parsed.get("coldstart") or {}
        serve_parsed["serve_coldstart_ms"] = cs.get("serve_coldstart_ms")
        serve_parsed["cold_compiles"] = cs.get("cold_compiles")
        serve_parsed["arena_speedup"] = (
            serve_parsed.get("arena") or {}).get("speedup")
        serve_path = os.path.join(out_dir, f"SERVE_manual_r{n:02d}.json")
        with open(serve_path, "w") as fh:
            json.dump(serve_parsed, fh, indent=1)
        record["serve_path"] = serve_path
        print(f"# wrote {serve_path}")
    ingest_parsed = (results.get("bench_ingest") or {}).get("parsed")
    if ingest_parsed:
        # the ingest leg runs --no-write; the window owns the artifact.
        # Like SERVE_manual_rN it is NOT auto-globbed by bench_history's
        # directory scan (that scan takes the CI INGEST_r* rounds) —
        # pass the file explicitly to fold a window point into the table
        ingest_parsed = dict(ingest_parsed, n=n, dry_run=dry_run)
        ingest_path = os.path.join(out_dir, f"INGEST_manual_r{n:02d}.json")
        with open(ingest_path, "w") as fh:
            json.dump(ingest_parsed, fh, indent=1)
        record["ingest_path"] = ingest_path
        print(f"# wrote {ingest_path}")
    fleet_parsed = (results.get("bench_fleet") or {}).get("parsed")
    if fleet_parsed:
        # the fleet leg runs --no-write; the window owns the artifact.
        # Same convention as INGEST_manual_rN: not auto-globbed by
        # bench_history (that scan takes the CI FLEET_r* rounds) — pass
        # the file explicitly to fold a window point into the trend
        fleet_parsed = dict(fleet_parsed, n=n, dry_run=dry_run)
        fleet_path = os.path.join(out_dir, f"FLEET_manual_r{n:02d}.json")
        with open(fleet_path, "w") as fh:
            json.dump(fleet_parsed, fh, indent=1)
        record["fleet_path"] = fleet_path
        print(f"# wrote {fleet_path}")
    explain_parsed = (results.get("bench_explain") or {}).get("parsed")
    if explain_parsed:
        explain_parsed = dict(explain_parsed, n=n, dry_run=dry_run)
        explain_path = os.path.join(out_dir,
                                    f"SERVE_explain_manual_r{n:02d}.json")
        with open(explain_path, "w") as fh:
            json.dump(explain_parsed, fh, indent=1)
        record["explain_path"] = explain_path
        print(f"# wrote {explain_path}")
    if "bench_serve" in results:
        st_path, st_events = export_serve_trace(art_dir)
        if st_path:
            record["serve_trace"] = os.path.relpath(st_path, out_dir)
            record["serve_trace_events"] = st_events
            print(f"# wrote {st_path} ({st_events} trace events)")
        flight_path = os.path.join(art_dir, "FLIGHT_serve.json")
        if os.path.isfile(flight_path):
            record["serve_flight"] = os.path.relpath(flight_path, out_dir)
    if bench_parsed:
        print(f"# headline: {bench_parsed.get('value')} "
              f"{bench_parsed.get('unit')} "
              f"(vs_baseline {bench_parsed.get('vs_baseline')}, "
              f"backend {bench_parsed.get('backend', 'accelerator')})")
    print(f"# health: {health['verdict']} "
          f"({health['failures']} failures, schema "
          f"{'ok' if health['events_ok'] else 'PROBLEMS'})")
    if record["triage"]:
        tr = record["triage"]
        legs_s = ", ".join(f"{k}={v}" for k, v in sorted(
            tr["legs"].items()))
        print(f"# triage: {legs_s}")
    if record["board"]:
        b = record["board"]
        rec_units = sorted((b.get("reconciliation") or {})
                           .get("units", {}) or {})
        print(f"# board: {b.get('scrapes', 0)} scrapes, iteration "
              f"{b.get('iteration')}, reconciliation units "
              f"{rec_units or 'none'}")
    record["bench_path"] = bench_path
    record["health_path"] = health_path
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Probe for a live TPU backend and capture the "
                    "ROOFLINE first-window checklist the moment one "
                    "appears")
    ap.add_argument("--interval", type=float, default=60.0,
                    help="seconds between liveness probes (default 60)")
    ap.add_argument("--probe-timeout", type=int, default=120,
                    help="per-probe subprocess timeout (default 120)")
    ap.add_argument("--leg-timeout", type=int, default=1800,
                    help="per-checklist-leg timeout (default 1800)")
    ap.add_argument("--max-wait", type=float, default=0.0,
                    help="give up after this many seconds of probing "
                         "(0 = wait forever)")
    ap.add_argument("--once", action="store_true",
                    help="probe a single time instead of looping")
    ap.add_argument("--dry-run", action="store_true",
                    help="skip the probe gate and run the whole "
                         "checklist on the CPU backend at smoke sizes")
    ap.add_argument("--out", default=REPO,
                    help="artifact directory (default: repo root)")
    ap.add_argument("--round", type=int, default=0,
                    help="round number for the artifact names "
                         "(default: next free BENCH_manual_rN)")
    ap.add_argument("--legs", default="",
                    help="comma list restricting which checklist legs "
                         "run (bench,bench_profile,bench_maxbin63,"
                         "bench_unfused,bench_quant,bench_nofusedgrad,"
                         "bench_rank,prof_kernels,bench_serve,"
                         "bench_explain,bench_ingest,bench_fleet,trace); "
                         "default all")
    ap.add_argument("--wedge-retries", type=int, default=1,
                    help="times a wedge-shaped leg failure (timeout / "
                         "transient runtime error) is retried with "
                         "backoff before the leg is abandoned "
                         "(default 1; 0 restores the old behavior)")
    args = ap.parse_args(argv)
    only = {s.strip() for s in args.legs.split(",") if s.strip()} or None

    deadline = time.time() + args.max_wait if args.max_wait else None
    probes = 0
    while True:
        if args.dry_run:
            armed, backend = True, "cpu (dry-run)"
        else:
            armed, backend = probe_backend(args.probe_timeout)
        probes += 1
        if armed:
            n = args.round or next_round(args.out)
            print(f"# backend '{backend}' alive after {probes} probe(s); "
                  f"capturing window as round r{n:02d}", flush=True)
            rec = run_checklist(args.out, n, args.dry_run,
                                timeout=args.leg_timeout, backend=backend,
                                only=only,
                                wedge_retries=args.wedge_retries)
            # exit 0 only for a FULLY clean capture: every leg rc 0 and
            # (when the bench leg ran) a parsed headline line — a failed
            # trace/prof leg must be visible to cron wrappers even though
            # the artifacts were still written
            bench_ok = ("bench" not in (only or {"bench"}) or
                        rec["parsed"] is not None)
            return 0 if rec["rc"] == 0 and bench_ok else 2
        if args.once or (deadline and time.time() >= deadline):
            print(f"# no live backend after {probes} probe(s) "
                  f"(last: {backend or 'cpu'})", file=sys.stderr)
            return 3
        print(f"# probe {probes}: backend '{backend or 'cpu'}' — "
              f"sleeping {args.interval:g}s", flush=True)
        time.sleep(args.interval)


if __name__ == "__main__":
    sys.path.insert(0, REPO)
    sys.exit(main())
