"""CPU-smokeable fault-injection matrix: prove every recovery branch.

Runs the ``LGBM_TPU_FAULTS`` injection points against every recovery
mode in one process and emits a per-check verdict map, exactly like
``bench_serve.py --smoke`` — ``tools/run_suite.py`` runs it as the
``faults`` tier, so every suite round re-proves on CPU that:

- a TRANSIENT device failure retries with backoff and the final model is
  bit-identical to the no-fault run (retry is a pure re-execution);
- a FATAL failure under ``abort`` raises ``DeviceWedgedError`` AFTER
  writing a boundary checkpoint + flight dump, and resuming from that
  wedge checkpoint reproduces the no-fault model bit-exactly;
- ``fallback`` re-executes the step on the CPU backend and completes;
- a transient GRADIENT failure and a transient COLLECTIVE failure both
  retry clean;
- an injected serve-device failure degrades the session, and the
  periodic re-probe recovers it (health + metrics flip back);
- a failed CHECKPOINT write is survived (training never dies for it)
  and the loader skips a corrupted checkpoint for the previous valid
  one;
- a streamed-ingestion chunk fault (ISSUE 14, ``ingest_chunk``)
  retries to a bit-identical dataset, a fatal/corrupt chunk aborts
  loudly before anything bins, and a stalled chunk read is stamped;
- a truncated/corrupt persisted AOT executable (ISSUE 19) falls back
  to JIT LOUDLY (``aot_fallback`` event + fallback counter) with
  bit-identical predictions, and arena byte-budget pressure evicts a
  tenant that is transparently re-admitted — bit-identical — on its
  next request;
- the elastic fleet (ISSUE 20): a rank killed mid-iteration is
  detected, survivors roll back to the common checkpoint and resume to
  a bit-exact model; a killed COORDINATOR makes every surviving rank
  exit loudly (143) with a flight dump — never hang; an injected
  heartbeat stall is stamped ``fleet_stall`` without killing anyone;
  and a healed joiner folds back in mid-run to a final model bit-exact
  vs the never-failed oracle.

    python tools/fault_matrix.py --json      # one JSON verdict line
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="Fault-injection matrix")
    ap.add_argument("--json", action="store_true",
                    help="print a machine-readable verdict line")
    args = ap.parse_args(argv)

    import lightgbm_tpu as lgb
    from lightgbm_tpu import obs
    from lightgbm_tpu.robust import DeviceWedgedError, faults
    from lightgbm_tpu.robust.watchdog import guarded_call

    t0 = time.time()
    art = tempfile.mkdtemp(prefix="fault_matrix_")
    os.environ["LGBM_TPU_FLIGHT_DIR"] = art

    rng = np.random.default_rng(0)
    X = rng.normal(size=(500, 6))
    y = (X[:, 0] - 0.3 * X[:, 2] > 0).astype(np.float64)
    P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1, "bagging_fraction": 0.8, "bagging_freq": 2}

    def train(extra=None, n=6):
        p = dict(P)
        p.update(extra or {})
        ds = lgb.Dataset(X, label=y, params=p)
        b = lgb.train(p, ds, num_boost_round=n, verbose_eval=False)
        return b.model_to_string(num_iteration=-1).split("\nparameters:")[0]

    ref = train()

    # ---- device_execute x retry ------------------------------------
    faults.configure("device_execute:transient@iter=2")
    try:
        m = train({"tpu_on_device_error": "retry"})
        check("device_execute.retry.bit_identical", m == ref)
    except Exception as exc:  # noqa: BLE001
        check("device_execute.retry.bit_identical", False, repr(exc))
    faults.disarm()

    # ---- device_execute x abort (+ wedge checkpoint + resume) ------
    ckdir = os.path.join(art, "wedge_ckpt")
    faults.configure("device_execute:raise@iter=3")
    wedged = False
    try:
        train({"tpu_on_device_error": "abort", "tpu_checkpoint_dir": ckdir,
               "tpu_checkpoint_freq": 0})
    except DeviceWedgedError:
        wedged = True
    except SystemExit:
        pass
    faults.disarm()
    check("device_execute.abort.raises", wedged)
    cks = glob.glob(os.path.join(ckdir, "ckpt_*"))
    check("device_execute.abort.wedge_checkpoint", len(cks) == 1)
    check("device_execute.abort.flight_dumped",
          len(glob.glob(os.path.join(art, "FLIGHT_*.json"))) >= 1)
    try:
        m = train({"tpu_checkpoint_dir": ckdir, "tpu_checkpoint_freq": 0})
        check("device_execute.abort.resume_bit_identical", m == ref)
    except Exception as exc:  # noqa: BLE001
        check("device_execute.abort.resume_bit_identical", False, repr(exc))

    # ---- device_execute x fallback ---------------------------------
    faults.configure("device_execute:raise@iter=2")
    try:
        m = train({"tpu_on_device_error": "fallback"})
        check("device_execute.fallback.completes", m == ref)
    except Exception as exc:  # noqa: BLE001
        check("device_execute.fallback.completes", False, repr(exc))
    faults.disarm()

    # ---- gradients x retry -----------------------------------------
    faults.configure("gradients:transient@iter=1")
    try:
        m = train({"tpu_on_device_error": "retry"})
        check("gradients.retry.bit_identical", m == ref)
    except Exception as exc:  # noqa: BLE001
        check("gradients.retry.bit_identical", False, repr(exc))
    faults.disarm()

    # ---- collective x retry (direct guarded call) ------------------
    faults.configure("collective:transient")
    calls = []
    try:
        out = guarded_call(lambda: calls.append(1) or 42,
                           point="collective")
        check("collective.retry.recovers", out == 42 and len(calls) == 1)
    except Exception as exc:  # noqa: BLE001
        check("collective.retry.recovers", False, repr(exc))
    faults.disarm()

    # ---- stall detection -------------------------------------------
    obs.enable_flight(64)
    faults.configure("device_execute:sleep=0.25@iter=1")
    try:
        train({"tpu_wedge_timeout_s": 0.05})
        stalls = [e for e in obs.flight_snapshot()
                  if e.get("event") == "device_stall"]
        check("device_execute.stall.stamped", len(stalls) >= 1)
    except Exception as exc:  # noqa: BLE001
        check("device_execute.stall.stamped", False, repr(exc))
    faults.disarm()

    # ---- serve_device x probe-and-recover --------------------------
    from lightgbm_tpu.serve import PredictorSession
    ds = lgb.Dataset(X, label=y, params=dict(P))
    bst = lgb.train(dict(P), ds, num_boost_round=5, verbose_eval=False)
    faults.configure("serve_device:raise@call=1")
    sess = PredictorSession(bst, config=dict(
        P, tpu_serve_reprobe_s=0.05, tpu_serve_max_batch=128))
    p_ref = bst.predict(X[:16])
    out1 = sess.predict(X[:16])
    st1 = sess.stats()
    check("serve_device.degrades", bool(st1["degraded"])
          and st1["degraded_transitions"] == 1)
    check("serve_device.host_fallback_correct",
          np.allclose(out1, p_ref, atol=1e-6))
    time.sleep(0.11)
    out2 = sess.predict(X[:16])
    st2 = sess.stats()
    check("serve_device.reprobe_recovers",
          not st2["degraded"] and st2["recoveries"] == 1)
    check("serve_device.device_after_recovery_correct",
          np.allclose(out2, p_ref, atol=1e-6))
    sess.close()
    faults.disarm()

    # ---- explain-plane wedge: degrade + reprobe ISOLATED from predict
    # (ISSUE 10: the explain fault points landed after PR 7's matrix) --
    faults.configure("serve_explain_device:raise@call=1")
    sess2 = PredictorSession(bst, config=dict(
        P, tpu_serve_reprobe_s=0.05, tpu_serve_max_batch=128))
    try:
        xout = sess2.explain(X[:4])          # wedge fires -> host oracle
        stx = sess2.stats()
        # the TreeSHAP wedge degrades ONLY the explain plane; predict
        # keeps its device path (a shared flag would oscillate)
        check("serve_explain.degrade_isolated",
              bool(stx["explain_degraded"]) and not stx["degraded"])
        pok = np.allclose(sess2.predict(X[:16]), p_ref, atol=1e-6)
        check("serve_explain.predict_unaffected",
              pok and not sess2.stats()["degraded"])
        x_ref = bst.predict(X[:4], pred_contrib=True)
        check("serve_explain.host_fallback_correct",
              np.allclose(xout, x_ref, atol=1e-5))
        faults.disarm()                      # let the reprobe succeed
        time.sleep(0.11)
        xout2 = sess2.explain(X[:4])
        stx2 = sess2.stats()
        check("serve_explain.reprobe_recovers",
              not stx2["explain_degraded"]
              and np.allclose(xout2, x_ref, atol=1e-5))
    except Exception as exc:  # noqa: BLE001
        for name in ("serve_explain.degrade_isolated",
                     "serve_explain.predict_unaffected",
                     "serve_explain.host_fallback_correct",
                     "serve_explain.reprobe_recovers"):
            CHECKS.setdefault(name, False)
        check("serve_explain.no_crash", False, repr(exc))
    finally:
        sess2.close()
        faults.disarm()

    # ---- checkpoint_write fault is survived; corrupt ckpt skipped --
    ckdir2 = os.path.join(art, "ckpt2")
    faults.configure("checkpoint_write:raise@call=2")
    try:
        m = train({"tpu_checkpoint_dir": ckdir2, "tpu_checkpoint_freq": 2})
        check("checkpoint_write.fault_survived", m == ref)
    except Exception as exc:  # noqa: BLE001
        check("checkpoint_write.fault_survived", False, repr(exc))
    faults.disarm()
    cks = sorted(glob.glob(os.path.join(ckdir2, "ckpt_*")))
    if cks:
        # corrupt the newest checkpoint's state; the loader must fall
        # back to the previous valid one
        with open(os.path.join(cks[-1], "state.npz"), "ab") as fh:
            fh.write(b"garbage")
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.robust import CheckpointManager
        mgr = CheckpointManager(ckdir2)
        peeked = mgr.peek(Config.from_params(
            dict(P, tpu_checkpoint_dir=ckdir2, tpu_checkpoint_freq=2)))
        ok = (peeked is not None
              and peeked[0] != cks[-1]) if len(cks) > 1 else \
            (peeked is None)
        check("checkpoint.corrupt_newest_skipped", ok,
              f"picked {peeked and peeked[0]}, had {cks}")
    else:
        check("checkpoint.corrupt_newest_skipped", False,
              "no checkpoints written")

    # ---- online loop (ISSUE 12): refit fault / poisoned canary -----
    # a refresh that dies (injected fault) or produces garbage (NaN
    # leaves) must be a NON-event: no swap, old version still serving
    from lightgbm_tpu.config import Config as _Cfg
    from lightgbm_tpu.online import OnlineLoop, train_continue
    from lightgbm_tpu.serve import ModelRegistry
    from lightgbm_tpu.serve.registry import SwapRejected

    base_path = os.path.join(art, "online_base.txt")
    bst.save_model(base_path)
    ocfg = _Cfg.from_params(dict(
        P, tpu_serve_replicas=1, tpu_serve_rollback_watch_s=0.0,
        tpu_online_refit_every=100, tpu_online_window=400,
        tpu_online_decay=0.5))
    reg2 = ModelRegistry(config=ocfg)
    reg2.add_model("m", base_path)
    oloop = OnlineLoop(base_path, config=ocfg,
                       push=lambda p: reg2.swap("m", p), params=dict(P))
    faults.configure("online_refit:raise")
    oloop.ingest(X[:200], y[:200])
    rep = oloop.tick()
    faults.disarm()
    live = reg2.resolve("m").version
    check("online.refit_fault_no_swap",
          rep is not None and not rep["ok"] and oloop.versions == 0, rep)
    check("online.refit_fault_old_serving", live == 1, f"live v{live}")
    # poisoned candidate: NaN leaves bounce off the canary's finite gate
    import re as _re
    with open(base_path) as fh:
        txt = fh.read()
    poisoned = os.path.join(art, "online_poisoned.txt")
    with open(poisoned, "w") as fh:
        fh.write(_re.sub(
            r"^leaf_value=.*$",
            lambda m: "leaf_value=" + " ".join(
                ["nan"] * len(m.group(0).split("=")[1].split())),
            txt, flags=_re.MULTILINE))
    try:
        reg2.swap("m", poisoned)
        check("online.poisoned_canary_rejects", False, "swap accepted")
    except SwapRejected as exc:
        checks_map = (exc.report or {}).get("checks") or {}
        check("online.poisoned_canary_rejects",
              checks_map.get("finite") is False
              or checks_map.get("gate") is False, exc.report)
    check("online.poisoned_old_serving", reg2.resolve("m").version == 1)
    reg2.close()

    # ---- crash mid-train-continue -> bit-exact resume --------------
    Xn = rng.normal(size=(400, 6))
    yn = (Xn[:, 0] - 0.3 * Xn[:, 2] > 0).astype(np.float64)
    cont_p = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "verbose": -1}
    ref_cont = train_continue(base_path, Xn, yn, params=cont_p,
                              num_boost_round=4).model_to_string(
        num_iteration=-1).split("\nparameters:")[0]
    ckdir3 = os.path.join(art, "online_ckpt")
    crash_p = dict(cont_p, tpu_on_device_error="abort",
                   tpu_checkpoint_dir=ckdir3, tpu_checkpoint_freq=1)
    faults.configure("device_execute:raise@iter=8")  # 6 init + 2 new
    crashed = False
    try:
        train_continue(base_path, Xn, yn, params=crash_p,
                       num_boost_round=4)
    except DeviceWedgedError:
        crashed = True
    except SystemExit:
        pass
    faults.disarm()
    check("online.continue_crash_raises", crashed)
    try:
        m = train_continue(base_path, Xn, yn, params=crash_p,
                           num_boost_round=4).model_to_string(
            num_iteration=-1).split("\nparameters:")[0]
        check("online.continue_resume_bit_exact", m == ref_cont)
    except Exception as exc:  # noqa: BLE001
        check("online.continue_resume_bit_exact", False, repr(exc))

    # ---- streaming ingestion (ISSUE 14): chunk fault x recovery ----
    # a transient chunk-read fault retries to a BIT-IDENTICAL dataset,
    # a fatal one aborts loudly (never bins garbage), a corrupt chunk
    # (column-count drift) aborts loudly, and a stalled read is stamped
    from lightgbm_tpu.config import Config as _ICfg
    from lightgbm_tpu.ingest import ArraySource, IngestError, ingest_dataset

    icfg = _ICfg.from_params({"verbose": -1, "max_bin": 31})
    clean_ing = ingest_dataset(ArraySource(X, label=y, chunk_rows=100),
                               icfg)
    faults.configure("ingest_chunk:transient@call=3")
    try:
        d2 = ingest_dataset(ArraySource(X, label=y, chunk_rows=100), icfg)
        check("ingest.chunk_fault_retry_bit_identical",
              np.array_equal(d2.X_bin, clean_ing.X_bin))
    except Exception as exc:  # noqa: BLE001
        check("ingest.chunk_fault_retry_bit_identical", False, repr(exc))
    faults.disarm()

    faults.configure("ingest_chunk:raise@call=2")
    try:
        ingest_dataset(ArraySource(X, label=y, chunk_rows=100), icfg)
        check("ingest.fatal_chunk_aborts", False, "ingest completed")
    except (DeviceWedgedError, IngestError):
        check("ingest.fatal_chunk_aborts", True)
    faults.disarm()

    class _CorruptSource:  # column-count drift mid-stream
        group_sizes = None

        def __iter__(self):
            yield X[:100], {"label": y[:100]}
            yield X[100:200, :3], {"label": y[100:200]}

    try:
        ingest_dataset(_CorruptSource(), icfg)
        check("ingest.corrupt_chunk_aborts", False, "ingest completed")
    except IngestError:
        check("ingest.corrupt_chunk_aborts", True)

    faults.configure("ingest_chunk:sleep=0.25@call=2")
    try:
        ingest_dataset(ArraySource(X, label=y, chunk_rows=100),
                       _ICfg.from_params({"verbose": -1, "max_bin": 31,
                                          "tpu_wedge_timeout_s": 0.05}))
        ing_stalls = [e for e in obs.flight_snapshot()
                      if e.get("event") == "device_stall"
                      and e.get("point") == "ingest_chunk"]
        check("ingest.stall_stamped", len(ing_stalls) >= 1)
    except Exception as exc:  # noqa: BLE001
        check("ingest.stall_stamped", False, repr(exc))
    faults.disarm()

    # ---- ingest stall: cadence fires, no fresh rows -> skipped -----
    sloop = OnlineLoop(base_path, config=ocfg, push=None, params=dict(P))
    sloop.refresh_rows, sloop.refresh_s = 0, 0.01
    time.sleep(0.03)
    srep = sloop.tick()
    stall_events = [e for e in obs.flight_snapshot()
                    if e.get("event") == "online_refresh"
                    and e.get("skipped") == "ingest_stall"]
    check("online.ingest_stall_skipped",
          srep is not None and srep.get("skipped") == "ingest_stall"
          and sloop.versions == 0, srep)
    check("online.ingest_stall_stamped", len(stall_events) >= 1)

    # ---- AOT store (ISSUE 19): corrupt entry -> loud JIT fallback --
    # a present-but-garbage persisted executable must never crash or
    # poison output: the loader rejects it, stamps ``aot_fallback``,
    # bumps the fallback counter, and the JIT path serves bit-identical
    aotdir = os.path.join(art, "aot")
    warm = PredictorSession(bst, config=dict(
        P, tpu_serve_aot_dir=aotdir, tpu_serve_max_batch=64))
    warm.warmup()
    warm.close()
    aot_files = glob.glob(os.path.join(aotdir, "*.aot"))
    check("aot.store_written", len(aot_files) >= 1,
          f"{len(aot_files)} entries in {aotdir}")
    for p in aot_files:  # truncate every entry: present but garbage
        with open(p, "r+b") as fh:
            fh.truncate(max(1, os.path.getsize(p) // 3))
    obs.enable_flight(64)  # fresh ring so the fallbacks stand out
    s_cold = PredictorSession(bst, config=dict(
        P, tpu_serve_aot_dir=aotdir, tpu_serve_max_batch=64))
    try:
        out_cold = s_cold.predict(X[:16])
        aot_st = (s_cold.stats() or {}).get("aot") or {}
        fb_events = [e for e in obs.flight_snapshot()
                     if e.get("event") == "aot_fallback"]
        check("aot.corrupt_falls_back_loudly",
              aot_st.get("fallbacks", 0) >= 1 and len(fb_events) >= 1,
              aot_st)
        with PredictorSession(bst, config=dict(
                P, tpu_serve_max_batch=64)) as s_ref:
            check("aot.corrupt_bit_identical",
                  np.array_equal(out_cold, s_ref.predict(X[:16])))
    except Exception as exc:  # noqa: BLE001
        check("aot.corrupt_falls_back_loudly", False, repr(exc))
        CHECKS.setdefault("aot.corrupt_bit_identical", False)
    finally:
        s_cold.close()

    # ---- arena (ISSUE 19): byte pressure -> evict, then re-admit ---
    # an impossible budget forces LRU eviction on every admit; the
    # evicted tenant's next request transparently re-admits it and the
    # answer stays bit-identical to a dedicated per-model session
    from lightgbm_tpu.serve import ForestArena
    bst_b = lgb.train(dict(P), lgb.Dataset(X, label=y, params=dict(P)),
                      num_boost_round=4, verbose_eval=False)
    arena = ForestArena(budget_bytes=1, max_batch=64, max_wait_ms=1.0)
    try:
        arena.admit("ta", bst)
        arena.admit("tb", bst_b)  # budget evicts the LRU tenant 'ta'
        st_a = arena.stats()
        check("arena.pressure_evicts",
              st_a["evictions"] >= 1 and st_a["resident"] == 1, st_a)
        out_a = arena.predict(X[:16], model="ta")  # re-admits 'ta'
        st_b = arena.stats()
        with PredictorSession(bst, config=dict(
                P, tpu_serve_max_batch=64)) as s_ta:
            check("arena.readmit_transparent_bit_identical",
                  st_b["readmissions"] >= 1
                  and np.array_equal(out_a, s_ta.predict(X[:16])),
                  st_b)
    except Exception as exc:  # noqa: BLE001
        CHECKS.setdefault("arena.pressure_evicts", False)
        check("arena.readmit_transparent_bit_identical", False,
              repr(exc))
    finally:
        arena.close()

    # ---- elastic fleet (ISSUE 20): kill / coordinator / stall / rejoin
    from lightgbm_tpu.config import Config as _FCfg
    from lightgbm_tpu.fleet.launch import EVENTS, launch_fleet

    fdata = os.path.join(art, "fleet_train.tsv")
    frng = np.random.default_rng(3)
    FX = frng.normal(size=(120, 5))
    Fy = FX[:, 0] * 2.0 + np.sin(FX[:, 1]) \
        + frng.normal(scale=0.1, size=120)
    np.savetxt(fdata, np.column_stack([Fy, FX]), delimiter="\t",
               fmt="%.8f")

    def fleet_params(tag, **extra):
        p = {"task": "train", "objective": "regression", "data": fdata,
             "label_column": "0", "num_iterations": "12",
             "num_leaves": "7", "min_data_in_leaf": "5",
             "learning_rate": "0.1", "tpu_ingest": "true",
             "verbosity": "-1", "tpu_fleet": "3",
             "tpu_fleet_heartbeat_s": "3", "tpu_checkpoint_freq": "4",
             "tpu_fleet_dir": os.path.join(art, f"fleet_{tag}"),
             "output_model": os.path.join(art, f"fleet_{tag}.txt")}
        p.update({k: str(v) for k, v in extra.items()})
        return p

    def fleet_oracle(tag, p):
        """Never-failed single-process run of the same training args."""
        import subprocess
        single = {k: v for k, v in p.items()
                  if not k.startswith("tpu_fleet")}
        single["output_model"] = os.path.join(art, f"oracle_{tag}.txt")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO + os.pathsep
                   + os.environ.get("PYTHONPATH", ""))
        env.pop("LGBM_TPU_FAULTS", None)
        subprocess.run([sys.executable, "-m", "lightgbm_tpu",
                        *[f"{k}={v}" for k, v in single.items()]],
                       check=True, env=env, capture_output=True,
                       timeout=240)
        return open(single["output_model"]).read().split(
            "\nparameters:\n")[0]

    def tree_text(path):
        return open(path).read().split("\nparameters:\n")[0]

    def fleet_events(p):
        path = os.path.join(p["tpu_fleet_dir"], EVENTS)
        if not os.path.exists(path):
            return []
        return [json.loads(line) for line in open(path)]

    # rank killed mid-iteration -> survivors detect, roll back to the
    # common checkpoint, resume at the shrunk world, and the finished
    # model is bit-exact (heal off: the pure-shrink branch)
    p = fleet_params("kill", tpu_fleet_heal="false")
    try:
        res = launch_fleet(_FCfg.from_params(p), p, per_rank_env={
            1: {"LGBM_TPU_FAULTS": "fleet_die:raise@iter=6"}})
        ev = [e["name"] for e in fleet_events(p)]
        check("fleet.rank_killed.survivors_recover",
              res["rc"] == 0 and res["rcs"].get(1) == 137
              and "member_dead" in ev and "resize" in ev, res)
        check("fleet.rank_killed.bit_exact",
              tree_text(p["output_model"]) == fleet_oracle("kill", p))
    except Exception as exc:  # noqa: BLE001
        check("fleet.rank_killed.survivors_recover", False, repr(exc))
        CHECKS.setdefault("fleet.rank_killed.bit_exact", False)

    # coordinator killed -> every surviving rank exits 143 with a
    # flight dump, never hangs (recovery without the hub is impossible)
    p = fleet_params("coord", tpu_fleet_heal="false")
    fldir = os.path.join(art, "fleet_coord_flight")
    try:
        t_coord = time.time()
        res = launch_fleet(_FCfg.from_params(p), p, per_rank_env={
            0: {"LGBM_TPU_FAULTS": "fleet_die:raise@iter=6"},
            1: {"LGBM_TPU_FLIGHT": "64", "LGBM_TPU_FLIGHT_DIR": fldir},
            2: {"LGBM_TPU_FLIGHT": "64", "LGBM_TPU_FLIGHT_DIR": fldir}})
        wall = time.time() - t_coord
        check("fleet.coordinator_killed.loud_exit",
              res["rcs"].get(0) == 137
              and res["rcs"].get(1) == 143 and res["rcs"].get(2) == 143
              and wall < 60, res)
        dumps = glob.glob(os.path.join(fldir, "FLIGHT_*.json"))
        check("fleet.coordinator_killed.flight_dumped", len(dumps) >= 2,
              f"{len(dumps)} dumps in {fldir}")
    except Exception as exc:  # noqa: BLE001
        check("fleet.coordinator_killed.loud_exit", False, repr(exc))
        CHECKS.setdefault("fleet.coordinator_killed.flight_dumped", False)

    # heartbeat stall: one rank sleeps past stall_frac x heartbeat on
    # every iteration — stamped ``fleet_stall``, NOT killed, run clean
    p = fleet_params("stall", tpu_fleet_heartbeat_s="3",
                     tpu_fingerprint_freq="1", num_iterations="6")
    try:
        res = launch_fleet(_FCfg.from_params(p), p, per_rank_env={
            2: {"LGBM_TPU_FAULTS": "fleet_hb:sleep=2.0@n=-1"}})
        ev = fleet_events(p)
        stalls = [e for e in ev if e["name"] == "fleet_stall"]
        deaths = [e for e in ev if e["name"] == "member_dead"]
        check("fleet.stall.stamped_not_killed",
              res["ok"] and len(stalls) >= 1 and not deaths,
              {"res": res, "stalls": len(stalls), "deaths": deaths})
    except Exception as exc:  # noqa: BLE001
        check("fleet.stall.stamped_not_killed", False, repr(exc))

    # re-join after heal: iterations slowed fleet-wide so the healed
    # joiner's startup fits inside the remaining run — it must fold in
    # mid-run (a resize with joiners=1) and the final model must still
    # bit-match the never-failed oracle
    p = fleet_params("rejoin", num_iterations="40",
                     tpu_fleet_heartbeat_s="4", tpu_checkpoint_freq="5")
    slow = "fleet_hb:sleep=0.5@n=-1"
    try:
        res = launch_fleet(_FCfg.from_params(p), p, per_rank_env={
            0: {"LGBM_TPU_FAULTS": slow},
            1: {"LGBM_TPU_FAULTS": slow + ";fleet_die:raise@iter=6"},
            2: {"LGBM_TPU_FAULTS": slow}})
        ev = fleet_events(p)
        joins = [e for e in ev if e["name"] == "member_join_pending"]
        grows = [e for e in ev if e["name"] == "resize"
                 and e.get("joiners")]
        check("fleet.rejoin.folds_in_mid_run",
              res["ok"] and res["heals"] == 1 and joins and grows, res)
        check("fleet.rejoin.bit_exact_vs_never_failed",
              tree_text(p["output_model"]) == fleet_oracle("rejoin", p))
    except Exception as exc:  # noqa: BLE001
        check("fleet.rejoin.folds_in_mid_run", False, repr(exc))
        CHECKS.setdefault("fleet.rejoin.bit_exact_vs_never_failed", False)

    record = {
        "kind": "fault_matrix",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "artifacts_dir": art,
    }
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
