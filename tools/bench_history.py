"""Merge per-round bench results (+ telemetry digests) into a metric
trajectory table and flag regressions.

Every round the driver runs ``python bench.py`` and stores its one JSON
line (plus exit metadata) as ``BENCH_r{NN}.json``.  Those files answer
"what was the number THIS round"; nothing answered "is the number moving
the wrong way".  This tool does:

    python tools/bench_history.py [path ...] [--json] [--threshold 0.1]
                                  [--fail-on-regression]

``path`` entries are bench-round JSON files, serving-round files
(``SERVE_r*.json`` from ``tools/bench_serve.py``), online-loop rounds
(``ONLINE_r*.json`` from ``tools/online_smoke.py``), streaming-ingest
rounds (``INGEST_r*.json`` from ``tools/ingest_bench.py``), drift
rounds (``DRIFT_r*.json`` from ``tools/drift_report.py --smoke`` —
``drift_psi_max`` / ``quality_auc_delta`` trended, rounds with failed
checks flagged like canaries), multi-chip legs (``MULTICHIP_r*.json``,
driver-written — ``n_devices`` + ok trended, a device-count drop or an
ok->failed flip flagged like a mode regression), elastic-fleet rounds
(``FLEET_r*.json`` from ``tools/fleet_smoke.py`` — ``fleet_ranks`` /
``fleet_recoveries`` trended, failed checks flagged like canaries),
telemetry digest JSON files (``telemetry_report.py --json`` output), or
directories to glob for ``BENCH_r*.json`` + ``SERVE_r*.json`` +
``ONLINE_r*.json`` + ``INGEST_r*.json`` + ``DRIFT_r*.json`` +
``MULTICHIP_r*.json`` + ``FLEET_r*.json`` (default: the repo root).
Rounds whose bench produced no parseable line (``"parsed": null`` —
e.g. round 1's empty tail) are listed but carry no metrics.  Serving
rounds trend rows/s + p50/p99 + batch occupancy under their own
context, and a round that degraded to the host predictor is excluded
from baselines like a CPU-fallback canary.  A manual-window round whose
legs needed wedge retries (``wedge_retries`` > 0, stamped by
``tools/tpu_window.py``) is flagged "recovered" in the table —
distinguishable from clean rounds without being discarded (the backend
did answer in the end).

Regression flagging compares each metric of the LATEST comparable round
against the best earlier comparable round — comparable meaning the same
(backend, rows, iters, num_leaves, max_bin) context.  Rounds whose bench
ran on a degraded backend (``backend: cpu-fallback`` / ``cpu-forced``)
are wedge canaries: they are flagged in the table and excluded from the
regression baseline on BOTH sides, so a canary is never quoted as a perf
datapoint nor used as the bar a real round must clear.  A separate
INFORMATIONAL canary trend still surfaces ``per_iter_s`` alongside
throughput across same-context canary rounds, so a real speedup (e.g.
the batched split apply) is visible even when every recent round ran on
the CPU fallback.  Direction is per-metric (throughput up is good,
per-iter seconds down is good); a move worse than ``--threshold``
(default 10%) is flagged.  ``--fail-on-regression`` turns flags into
exit code 1 for CI use.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import List, Optional

# metric name (or prefix ending in *) -> True when higher is better
_DIRECTIONS = [
    ("value", True),
    ("vs_baseline", True),
    ("train_auc", True),
    ("train_ndcg10", True),
    ("rank_row_iters_per_s", True),
    ("rank_vs_baseline", True),
    ("rank_train_ndcg10", True),
    ("kernel_roofline/*", True),
    # trace-attributed measured rooflines (ISSUE 18, obs/xprof.py): the
    # fraction of the analytic roofline each kernel actually achieves
    # in a profiler window — the MEASURED companion of the
    # host-bracketed kernel_roofline estimate above
    ("kernel_measured/*", True),
    # wave-pipeline stamps (ISSUE 8): more kernel launches per tree, or a
    # capacity drop, is a scheduling regression even when throughput
    # noise hides it
    ("waves_per_tree", False),
    ("wave_capacity", True),
    # quantized/fused/overlap pipeline stamps (ISSUE 11): HBM bytes the
    # fused gradient pass saved per iteration and the fraction of waves
    # whose kernel co-ran with a deferred scan — both higher-is-better
    ("grad_hbm_bytes_saved", True),
    ("overlap_frac", True),
    ("per_iter_s", False),
    ("rank_per_iter_s", False),
    ("compile_s", False),
    ("rank_compile_s", False),
    ("binning_s", False),
    ("rank_binning_s", False),
    ("implied_higgs_500iter_s", False),
    ("implied_mslr_500iter_s", False),
    ("peak_hbm_bytes", False),
    # serving rounds (SERVE_r*.json, tools/bench_serve.py)
    ("serve_rows_per_s", True),
    ("serve_p50_ms", False),
    ("serve_p99_ms", False),
    ("serve_open_p99_ms", False),
    ("serve_explain_p99_ms", False),
    ("serve_occupancy", True),
    ("serve_server_p99_ms", False),
    ("serve_slo_burn", False),
    ("serve_client_server_skew", False),
    # hot-swap leg (ISSUE 10, bench_serve.py swap_leg): the p99 of
    # requests completing inside the swap window, the steady-state p99
    # beside it, and how many swaps bounced to a rollback
    ("serve_swap_blip_p99_ms", False),
    ("serve_steady_p99_ms", False),
    ("serve_rollbacks", False),
    # zero-cold-start + arena legs (ISSUE 19, bench_serve.py): fresh
    # subprocess exec -> request-#1 response with the AOT store armed,
    # the request-#1 latency itself, the cold compile count (0 IS the
    # contract — any growth means the store stopped covering a bucket),
    # and the arena-vs-per-model-sessions throughput ratio under the
    # Zipf tenant mix
    ("serve_coldstart_ms", False),
    ("serve_request1_ms", False),
    ("serve_cold_compiles", False),
    ("serve_arena_speedup", True),
    # online-loop rounds (ONLINE_r*.json, tools/online_smoke.py): how
    # long a refresh takes end to end (refit + save + canary-gated
    # swap) and how many refreshed versions made it through the gate
    ("online_refresh_s", False),
    ("online_swap_ok", True),
    # streaming-ingestion rounds (INGEST_r*.json, tools/ingest_bench.py):
    # two-pass construction throughput and the traced peak of the
    # bounded-memory proof (growth = the O(chunk + bins) contract
    # eroding)
    ("ingest_rows_per_s", True),
    ("ingest_wall_s", False),
    ("peak_traced_bytes", False),
    # drift rounds (DRIFT_r*.json, tools/drift_report.py --smoke): the
    # shifted-replay PSI (the detection margin — shrinking toward the
    # warn threshold means the plane is losing sensitivity) and the
    # label-flip windowed AUC drop the quality tracker caught
    ("drift_psi_max", True),
    ("drift_psi_iid", False),
    ("quality_auc_delta", True),
    # multi-chip legs (MULTICHIP_r*.json, driver-written): how many
    # devices the distributed leg actually saw, and whether it passed —
    # the categorical drop/flip companion lives in
    # find_device_regressions
    ("n_devices", True),
    ("multichip_ok", True),
    # elastic-fleet rounds (FLEET_r*.json, tools/fleet_smoke.py): the
    # gang world size, and how long the whole smoke took.  Recoveries
    # trend as a series without a direction — the kill leg makes
    # exactly one heal by construction, so neither more nor fewer is
    # "better"; a change shows in the table, not the regression gate
    ("fleet_ranks", True),
    ("fleet_wall_s", False),
]

# a swap blip worse than this multiple of the steady p99 is flagged: the
# hot swap is supposed to be invisible to traffic — a 2x p99 excursion
# means the flip (pack/canary/fresh-bucket compiles) is leaking into the
# request path
_SWAP_BLIP_FLAG = 2.0

# a trace-measured kernel more than this multiple off its analytic
# model (in either direction) is flagged: the cost models arbitrate the
# repo's perf claims, so a 2x divergence means either the kernel or the
# model is lying (ISSUE 18)
_DIVERGENCE_FLAG = 2.0

# the headline columns of the human table, in order
_TABLE_COLS = ["value", "vs_baseline", "per_iter_s", "compile_s",
               "train_auc", "waves_per_tree", "rank_row_iters_per_s",
               "peak_hbm_bytes", "serve_p99_ms", "serve_server_p99_ms",
               "serve_occupancy", "n_devices", "multichip_ok",
               "fleet_ranks", "fleet_recoveries"]

_CONTEXT_KEYS = ("backend", "rows", "iters", "num_leaves", "max_bin")

# client-observed p99 more than this multiple of the server-side p99 is
# flagged: the excess lives in the network / front-end queue, not the
# session (tools/bench_serve.py embeds both views per round)
_SKEW_FLAG = 3.0


def metric_direction(name: str) -> Optional[bool]:
    """True = higher is better, False = lower, None = untracked."""
    for pat, up in _DIRECTIONS:
        if pat.endswith("*"):
            if name.startswith(pat[:-1]):
                return up
        elif name == pat:
            return up
    return None


def _round_tag(path: str, payload: dict) -> str:
    m = re.search(r"r(\d+)", os.path.basename(path))
    if m:
        return f"r{int(m.group(1)):02d}"
    n = payload.get("n")
    return f"r{int(n):02d}" if isinstance(n, int) else os.path.basename(path)


def _apply_triage(row: dict, payload: dict) -> None:
    """Fold the window's own failure classification (ISSUE 17) into the
    row: WHY a round produced no clean point — rendered verbatim so a
    timeout round never reads like a code regression."""
    tri = payload.get("triage")
    if not (isinstance(tri, dict) and tri.get("legs")):
        return
    row["triage"] = dict(tri["legs"])
    legs_s = ", ".join(f"{k}:{v}" for k, v in sorted(tri["legs"].items()))
    row["note"] = ((row.get("note", "") + "; ") if row.get("note")
                   else "") + f"triage[{legs_s}]"


def load_round(path: str) -> dict:
    """One trajectory row from a bench-round file or a telemetry digest.

    Returns {"round", "context", "metrics", "note"?}; metrics is flat
    {name: number} with telemetry-derived entries namespaced
    (``phase_s/<phase>``, ``kernel_roofline/<kernel>``)."""
    with open(path) as fh:
        payload = json.load(fh)
    row = {"round": _round_tag(path, payload), "path": path, "metrics": {}}
    parsed = payload.get("parsed", payload)
    if parsed is None:
        # the fully-failed window: no bench line at all — the triage
        # block (when the window wrote one) and any trace-attributed
        # measured rows are the only story the row can tell
        row["note"] = "no parsed bench line"
        row["context"] = None
        _apply_triage(row, payload)
        _fold_measured(row, {}, payload)
        return row
    if parsed.get("kind") == "ingest":  # a tools/ingest_bench.py round
        row["context"] = ("ingest", parsed.get("backend"),
                          parsed.get("rows"), parsed.get("features"),
                          parsed.get("chunk_rows"), parsed.get("memmap"))
        for name in ("ingest_rows_per_s", "ingest_wall_s",
                     "peak_traced_bytes", "rows"):
            v = parsed.get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        checks = parsed.get("checks") or {}
        failed = [k for k, v in checks.items() if not v]
        if failed:
            row["note"] = ("ingest checks FAILED: " + ", ".join(failed)
                           + " — excluded from baselines")
            row["canary"] = "ingest-failed"
        return row
    if parsed.get("kind") == "fleet" or "fleet_ranks" in parsed:
        # a tools/fleet_smoke.py round (ISSUE 20): the 3-process
        # elastic-fleet smoke — world size + recovery count trended
        row["context"] = ("fleet", parsed.get("fleet_ranks"))
        for name, v in (("fleet_ranks", parsed.get("fleet_ranks")),
                        ("fleet_recoveries",
                         parsed.get("fleet_recoveries")),
                        ("fleet_wall_s", parsed.get("wall_s"))):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        checks = parsed.get("checks") or {}
        failed = [k for k, v in checks.items() if not v]
        if failed:
            row["note"] = ("fleet checks FAILED: " + ", ".join(failed)
                           + " — excluded from baselines")
            row["canary"] = "fleet-failed"
        return row
    if "n_devices" in parsed and "kind" not in parsed:
        # a driver-written MULTICHIP_r*.json leg: how many devices the
        # distributed run saw, and whether it passed.  Skipped legs
        # (no multi-device backend in the container) are canaries —
        # evidence the gate ran, never a distributed datapoint
        row["context"] = ("multichip",)
        row["metrics"]["n_devices"] = float(parsed["n_devices"])
        row["metrics"]["multichip_ok"] = float(bool(parsed.get("ok")))
        if parsed.get("skipped"):
            row["canary"] = "multichip-skipped"
            row["note"] = ("distributed leg skipped — excluded from "
                           "baselines")
        elif not parsed.get("ok"):
            row["canary"] = "multichip-failed"
            row["note"] = (f"multichip leg FAILED (rc {parsed.get('rc')})"
                           " — excluded from baselines")
        return row
    if parsed.get("kind") == "online":  # a tools/online_smoke.py round
        row["context"] = ("online", parsed.get("backend"))
        for name in ("online_refresh_s", "online_swap_ok",
                     "online_swap_rejected", "rows_ingested"):
            v = parsed.get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        checks = parsed.get("checks") or {}
        failed = [k for k, v in checks.items() if not v]
        if failed:
            row["note"] = ("online checks FAILED: " + ", ".join(failed)
                           + " — excluded from baselines")
            row["canary"] = "online-failed"
        return row
    if parsed.get("kind") == "drift":  # a tools/drift_report.py round
        row["context"] = ("drift", parsed.get("backend"))
        for name in ("drift_psi_max", "drift_psi_iid",
                     "quality_auc_delta"):
            v = parsed.get(name)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        checks = parsed.get("checks") or {}
        failed = [k for k, v in checks.items() if not v]
        if failed:
            # a failed check means the differential itself broke (false
            # alarm or missed shift) — flagged like a canary round, its
            # scores never join the baseline window
            row["note"] = ("drift checks FAILED: " + ", ".join(failed)
                           + " — excluded from baselines")
            row["canary"] = "drift-failed"
        return row
    if parsed.get("kind") == "serve":  # a bench_serve.py round
        row["context"] = ("serve", parsed.get("backend"),
                          parsed.get("trees"), parsed.get("max_batch"))
        closed = parsed.get("closed") or {}
        opened = parsed.get("open") or {}
        server = parsed.get("server") or {}
        for name, v in (("serve_rows_per_s", closed.get("rows_per_s")),
                        ("value", closed.get("rows_per_s")),
                        ("serve_p50_ms", closed.get("p50_ms")),
                        ("serve_p99_ms", closed.get("p99_ms")),
                        ("serve_open_p99_ms", opened.get("p99_ms")),
                        # mixed-load TreeSHAP leg (bench_serve.py
                        # --explain-frac): client-observed explain p99
                        ("serve_explain_p99_ms",
                         opened.get("explain_p99_ms")),
                        ("serve_occupancy", parsed.get("occupancy")),
                        ("serve_server_p99_ms", server.get("p99_ms")),
                        ("serve_slo_burn", server.get("slo_burn")),
                        ("jax_compiles", parsed.get("compiles"))):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        # hot-swap leg (bench_serve.py swap_leg): blip vs steady p99 +
        # rollback count.  A blip worse than _SWAP_BLIP_FLAG x steady is
        # flagged here so a leaky flip is visible in the table even
        # before the regression pass runs
        sw = parsed.get("swap") or {}
        for name, v in (("serve_swap_blip_p99_ms",
                         sw.get("swap_blip_p99_ms")),
                        ("serve_steady_p99_ms", sw.get("steady_p99_ms")),
                        ("serve_rollbacks", sw.get("rollbacks"))):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        blip = row["metrics"].get("serve_swap_blip_p99_ms")
        steady = row["metrics"].get("serve_steady_p99_ms")
        if blip and steady and blip > _SWAP_BLIP_FLAG * steady:
            row["swap_blip"] = round(blip / steady, 2)
            row["note"] = ((row.get("note", "") + "; ")
                           if row.get("note") else "") + \
                f"swap blip p99 {blip / steady:.1f}x steady p99"
        if sw.get("rollbacks"):
            row["note"] = ((row.get("note", "") + "; ")
                           if row.get("note") else "") + \
                f"{sw['rollbacks']} rollback(s) during the swap leg"
        # client-vs-server p99 skew: the server-side number (session
        # submit->result) excludes HTTP/network and client queueing — a
        # big ratio means latency is accumulating OUTSIDE the session
        # (network or front-end queue pathology), which no server-side
        # metric would ever show
        cp99 = row["metrics"].get("serve_p99_ms")
        sp99 = row["metrics"].get("serve_server_p99_ms")
        if cp99 and sp99:
            skew = round(cp99 / sp99, 3) if sp99 > 0 else None
            if skew is not None:
                row["metrics"]["serve_client_server_skew"] = skew
                if skew > _SKEW_FLAG:
                    row["note"] = (row.get("note", "") + "; " if
                                   row.get("note") else "") + \
                        f"client p99 {skew:g}x server p99 — " \
                        "network/queue pathology"
        # zero-cold-start leg (ISSUE 19, bench_serve.py coldstart_leg):
        # the AOT-on boot + request-#1 numbers, and the cold compile
        # count — nonzero on a warmed store is called out the way a
        # rollback is, even before the regression pass runs
        cs = parsed.get("coldstart") or {}
        for name, v in (("serve_coldstart_ms",
                         cs.get("serve_coldstart_ms")),
                        ("serve_request1_ms", cs.get("request1_ms")),
                        ("serve_cold_compiles",
                         cs.get("cold_compiles"))):
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][name] = float(v)
        if isinstance(cs.get("cold_compiles"), int) \
                and cs["cold_compiles"] > 0:
            row["note"] = ((row.get("note", "") + "; ")
                           if row.get("note") else "") + \
                f"{cs['cold_compiles']} JIT compile(s) on a warmed-" \
                "store cold boot"
        # arena leg (ISSUE 19, bench_serve.py arena_leg): cross-model
        # coalescing throughput vs dedicated per-model sessions
        ar = parsed.get("arena") or {}
        if isinstance(ar.get("speedup"), (int, float)) \
                and not isinstance(ar.get("speedup"), bool):
            row["metrics"]["serve_arena_speedup"] = float(ar["speedup"])
        # serving mode stamp: did the cold boot actually ride persisted
        # executables?  find_mode_regressions flags an on -> off flip
        # exactly like fused_sibling — a disarmed store posts the same
        # green checks while silently re-paying JIT on every boot
        if cs:
            row["mode"] = {"serve_aot": bool(
                (cs.get("aot_on") or {}).get("aot_buckets"))}
        if parsed.get("degraded"):
            row["canary"] = "serve-degraded"
            row["note"] = "degraded to host predictor — excluded from " \
                          "baselines"
        return row
    if "per_iteration" in parsed:  # a telemetry_report.py --json digest
        row["context"] = ("telemetry",)
        if parsed.get("cum_row_iters_per_s"):
            row["metrics"]["value"] = float(parsed["cum_row_iters_per_s"])
        for k, v in (parsed.get("phase_s") or {}).items():
            row["metrics"][f"phase_s/{k}"] = float(v)
        for k, v in (parsed.get("metrics_last") or {}).items():
            row["metrics"][k] = float(v)
        _fold_digest(row["metrics"], parsed)
        return row
    row["context"] = tuple(parsed.get(k) for k in _CONTEXT_KEYS)
    wr = payload.get("wedge_retries")
    if isinstance(wr, int) and wr > 0:
        # a RECOVERED round (tools/tpu_window.py retried wedged legs):
        # the numbers are real — the backend answered in the end — but
        # the flag distinguishes them from clean rounds when judging a
        # flaky window
        row["recovered"] = wr
        row["metrics"]["wedge_retries"] = float(wr)
        row["note"] = ((row.get("note", "") + "; ") if row.get("note")
                       else "") + f"recovered after {wr} wedge retr" \
            f"{'y' if wr == 1 else 'ies'}"
    backend = parsed.get("backend")
    if backend:
        # cpu-fallback / cpu-forced rounds are wedge CANARIES: evidence
        # the machinery still runs, never perf datapoints.  They are
        # excluded from regression baselines entirely (find_regressions)
        # and flagged in the table so a degraded number is never quoted
        # as a trajectory point (VERDICT round-5 weak #4).
        row["canary"] = str(backend)
        row["note"] = f"{backend} canary — excluded from baselines"
    # triage comes after the canary note, which assigns rather than
    # appends
    _apply_triage(row, payload)
    for k, v in parsed.items():
        if isinstance(v, bool) or k == "n":
            continue
        if isinstance(v, (int, float)):
            row["metrics"][k] = v
    if isinstance(parsed.get("kernel_roofline"), dict):
        for k, v in parsed["kernel_roofline"].items():
            row["metrics"][f"kernel_roofline/{k}"] = float(v)
    _fold_measured(row, parsed, payload)
    td = parsed.get("telemetry")
    if isinstance(td, dict):
        _fold_digest(row["metrics"], td)
    # wave-pipeline mode stamps (non-numeric — hist_mode is a string,
    # fused_sibling a bool — so the numeric fold above skips them): kept
    # on the row for find_mode_regressions, bench.py flat fields first,
    # the embedded digest's wave_pipeline section as fallback
    wp = (td.get("wave_pipeline") if isinstance(td, dict) else None) or {}
    mode = {}
    for k in ("hist_mode", "fused_sibling", "fused_grad"):
        v = parsed.get(k, wp.get(k))
        if v is not None:
            mode[k] = v
    if mode:
        row["mode"] = mode
    return row


def _fold_measured(row: dict, parsed: dict, payload: dict) -> None:
    """Fold measured-roofline rows (ISSUE 18) into a trajectory row.

    bench.py embeds a flat ``{kernel: roofline_frac}`` dict on the
    bench line; tpu_window.py embeds the full ``kernel_measured`` row
    list at the record's top level.  Both trend as
    ``kernel_measured/<kernel>``, and the full rows ride on the row
    for ``find_measured_divergence``."""
    km = parsed.get("kernel_measured")
    if isinstance(km, dict):
        for k, v in km.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                row["metrics"][f"kernel_measured/{k}"] = float(v)
    km_rows = payload.get("kernel_measured")
    if isinstance(km_rows, list):
        measured = [r for r in km_rows
                    if isinstance(r, dict) and r.get("kernel")]
        for r in measured:
            frac = r.get("roofline_frac")
            if isinstance(frac, (int, float)):
                row["metrics"].setdefault(
                    f"kernel_measured/{r['kernel']}", float(frac))
        if measured:
            row["measured"] = measured


def _fold_digest(metrics: dict, digest: dict) -> None:
    """Pull trajectory-worthy numbers out of an obs digest."""
    wp = digest.get("wave_pipeline") or {}
    for k in ("waves_per_tree", "wave_capacity"):
        if isinstance(wp.get(k), (int, float)):
            metrics.setdefault(k, float(wp[k]))
    counters = digest.get("counters") or {}
    if "jax/compiles" in counters:
        metrics.setdefault("jax_compiles", float(counters["jax/compiles"]))
    mem = digest.get("memory") or {}
    if mem.get("peak_bytes"):
        metrics.setdefault("peak_hbm_bytes", float(mem["peak_bytes"]))
    for k, v in (digest.get("kernels") or {}).items():
        metrics.setdefault(f"kernel_roofline/{k}",
                           float(v.get("roofline_frac", 0.0)))
    for k, v in ((digest.get("xprof") or {}).get("kernels") or {}).items():
        if isinstance(v, dict) and v.get("roofline_frac") is not None:
            metrics.setdefault(f"kernel_measured/{k}",
                               float(v["roofline_frac"]))
    comp = digest.get("compile") or {}
    for name in ("cache_hits", "cache_misses", "retraces"):
        if isinstance(comp.get(name), (int, float)):
            metrics.setdefault(f"compile_{name}", float(comp[name]))


def collect(paths: List[str]) -> List[dict]:
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "BENCH_r*.json"))))
            files.extend(sorted(glob.glob(os.path.join(p, "SERVE_r*.json"))))
            files.extend(sorted(glob.glob(os.path.join(p, "ONLINE_r*.json"))))
            files.extend(sorted(glob.glob(os.path.join(p, "INGEST_r*.json"))))
            files.extend(sorted(glob.glob(os.path.join(p, "DRIFT_r*.json"))))
            files.extend(sorted(glob.glob(
                os.path.join(p, "MULTICHIP_r*.json"))))
            files.extend(sorted(glob.glob(os.path.join(p, "FLEET_r*.json"))))
        else:
            files.append(p)
    rows = []
    for f in files:
        try:
            rows.append(load_round(f))
        except (OSError, ValueError) as exc:
            rows.append({"round": os.path.basename(f), "path": f,
                         "context": None, "metrics": {},
                         "note": f"unreadable: {exc}"})
    rows.sort(key=lambda r: r["round"])
    return rows


def find_regressions(rows: List[dict], threshold: float) -> List[dict]:
    """Latest comparable round vs the best earlier comparable value, per
    tracked metric.  Canary rounds (degraded-backend runs, see
    ``load_round``) participate on NEITHER side of the comparison."""
    rows = [r for r in rows if not r.get("canary")]
    latest = next((r for r in reversed(rows) if r["metrics"]), None)
    if latest is None:
        return []
    prior = [r for r in rows
             if r is not latest and r["metrics"]
             and r["context"] == latest["context"]]
    if not prior:
        return []
    out = []
    for name, cur in latest["metrics"].items():
        up = metric_direction(name)
        if up is None:
            continue
        vals = [(r["round"], r["metrics"][name]) for r in prior
                if name in r["metrics"]]
        if not vals:
            continue
        best_round, best = (max if up else min)(vals, key=lambda rv: rv[1])
        if not best:
            continue
        change = (cur - best) / abs(best)
        worse = -change if up else change
        if worse > threshold:
            out.append({
                "metric": name, "round": latest["round"],
                "value": cur, "best": best, "best_round": best_round,
                "change_frac": round(change, 4),
                "direction": "higher_is_better" if up
                else "lower_is_better",
            })
    return sorted(out, key=lambda r: -abs(r["change_frac"]))


def find_mode_regressions(rows: List[dict]) -> List[dict]:
    """Wave-pipeline MODE downgrades, flagged like perf regressions
    (ISSUE 8): a round whose histogram precision mode changed, or whose
    in-kernel sibling fusion silently flipped off, against the most
    recent comparable prior round.  These are categorical, not numeric —
    a bf16 round can post a better throughput while computing a worse
    histogram, which no threshold on ``value`` would ever catch.
    (waves_per_tree / wave_capacity drift is numeric and handled by
    ``find_regressions``.)"""
    rows = [r for r in rows if not r.get("canary")]
    latest = next((r for r in reversed(rows) if r.get("mode")), None)
    if latest is None:
        return []
    prior = next((r for r in reversed(rows)
                  if r is not latest and r.get("mode")
                  and r["context"] == latest["context"]), None)
    if prior is None:
        return []
    out = []
    lm, pm = latest["mode"], prior["mode"]
    for knob in ("fused_sibling", "fused_grad", "serve_aot"):
        # a fused pass silently flipping off is a pipeline downgrade
        # even when throughput noise hides it (fused_grad joins
        # fused_sibling in ISSUE 11 — the unfused twin re-pays the [N]
        # g/h round-trip every iteration; serve_aot joins in ISSUE 19 —
        # a disarmed executable store re-pays the full pow2 compile
        # family on every replica boot)
        if pm.get(knob) is True and lm.get(knob) is False:
            out.append({"metric": knob, "round": latest["round"],
                        "value": "off", "prior": "on",
                        "prior_round": prior["round"]})
    if (lm.get("hist_mode") and pm.get("hist_mode")
            and lm["hist_mode"] != pm["hist_mode"]):
        # ANY hist-mode change is flagged — which covers the ISSUE 11
        # downgrade of interest (a quantized int16/int8 round silently
        # reverting to an f32-family mode re-pays the full vector
        # stream and MXU passes)
        out.append({"metric": "hist_mode", "round": latest["round"],
                    "value": lm["hist_mode"], "prior": pm["hist_mode"],
                    "prior_round": prior["round"]})
    return out


def find_device_regressions(rows: List[dict]) -> List[dict]:
    """Multi-chip CATEGORICAL flags (ISSUE 20): the latest real (non-
    skipped) ``MULTICHIP_r*`` leg against the most recent real prior
    one — a device-count drop (the lease handed back a smaller slice,
    or the mesh config silently shrank) and an ok -> failed flip are
    both regressions no throughput threshold would catch.  Skipped
    legs (no multi-device backend in the container) participate on
    neither side, like canaries in ``find_regressions``."""
    mc = [r for r in rows
          if r.get("context") == ("multichip",)
          and r.get("canary") != "multichip-skipped"]
    if len(mc) < 2:
        return []
    latest, prior = mc[-1], mc[-2]
    out = []
    ln = latest["metrics"].get("n_devices")
    pn = prior["metrics"].get("n_devices")
    if ln is not None and pn is not None and ln < pn:
        out.append({"metric": "n_devices", "round": latest["round"],
                    "value": ln, "prior": pn,
                    "prior_round": prior["round"]})
    if (prior["metrics"].get("multichip_ok") == 1.0
            and latest["metrics"].get("multichip_ok") == 0.0):
        out.append({"metric": "multichip_ok", "round": latest["round"],
                    "value": "failed", "prior": "ok",
                    "prior_round": prior["round"]})
    return out


def find_measured_divergence(rows: List[dict],
                             factor: float = _DIVERGENCE_FLAG
                             ) -> List[dict]:
    """Measured-vs-model divergence (ISSUE 18): kernels on the latest
    non-canary round carrying trace-attributed measured rows whose
    roofline fraction is more than ``factor`` x off the analytic model
    in either direction — ``frac < 1/factor`` means the kernel runs far
    off the roofline the model promises (a real perf bug or a wrong
    machine-peak assumption), ``frac > factor`` means the model
    under-prices the op, so every prediction built on it (wave
    scheduling, reconciliation, A/B expectations) is wrong.  Reported
    and exit-code gated like ``find_mode_regressions``: categorical
    flags a threshold on throughput would never catch."""
    rows = [r for r in rows if not r.get("canary")]
    latest = next(
        (r for r in reversed(rows)
         if any(k.startswith("kernel_measured/") for k in r["metrics"])),
        None)
    if latest is None:
        return []
    out = []
    for k in sorted(latest["metrics"]):
        if not k.startswith("kernel_measured/"):
            continue
        frac = latest["metrics"][k]
        if frac <= 0:
            continue
        if frac > factor or frac < 1.0 / factor:
            out.append({
                "metric": k, "round": latest["round"],
                "roofline_frac": round(frac, 4),
                "divergence": round(max(frac, 1.0 / frac), 2),
                "side": ("model-underprices" if frac > 1
                         else "off-roofline"),
            })
    return sorted(out, key=lambda r: -r["divergence"])


def find_swap_blips(rows: List[dict]) -> List[dict]:
    """Serving rounds whose hot-swap blip p99 exceeded
    ``_SWAP_BLIP_FLAG`` x their steady p99 (stamped by ``load_round``),
    reported like mode regressions: categorical flags the numeric
    threshold pass would miss (a blip can double while the steady p99
    improves)."""
    return [{"metric": "swap_blip_p99_ms", "round": r["round"],
             "value": r["metrics"].get("serve_swap_blip_p99_ms"),
             "steady": r["metrics"].get("serve_steady_p99_ms"),
             "ratio": r["swap_blip"]}
            for r in rows if r.get("swap_blip")]


def canary_trend(rows: List[dict]) -> List[dict]:
    """per_iter_s + throughput trajectory across CANARY rounds of the
    same context.  Canaries never enter regression baselines
    (``find_regressions`` drops them), which also meant a perf win was
    INVISIBLE when consecutive rounds all ran on the CPU fallback — this
    surfaces per-iteration seconds alongside throughput for those rounds
    as an informational trend (never a gate): a partition-path speedup
    shows up as a per_iter_s drop between canaries even without a TPU
    datapoint."""
    prev: dict = {}
    out = []
    for r in rows:
        if not r.get("canary") or not r["metrics"]:
            continue
        ent = {"round": r["round"], "backend": r.get("canary"),
               "per_iter_s": r["metrics"].get("per_iter_s"),
               "value": r["metrics"].get("value")}
        p = prev.get(r["context"])
        if p:
            for m in ("per_iter_s", "value"):
                cur, base = ent.get(m), p.get(m)
                if cur is not None and base:
                    ch = (cur - base) / abs(base)
                    ent[f"{m}_change_frac"] = round(ch, 4)
        prev[r["context"]] = ent
        out.append(ent)
    return out


def render(rows: List[dict], regressions: List[dict],
           mode_regressions: List[dict] = (),
           swap_blips: List[dict] = (),
           measured_divergence: List[dict] = (),
           device_regressions: List[dict] = ()) -> str:
    cols = [c for c in _TABLE_COLS
            if any(c in r["metrics"] for r in rows)]
    out = [f"{'round':<6}{'context':<34}"
           + "".join(f"{c:>22}" for c in cols)]
    for r in rows:
        ctx = "-" if r["context"] is None else \
            ",".join(str(x) for x in r["context"])
        line = f"{r['round']:<6}{ctx[:33]:<34}"
        for c in cols:
            v = r["metrics"].get(c)
            if v is None:
                line += f"{'-':>22}"
            elif abs(v) >= 1e6:
                line += f"{v:>22,.0f}"
            else:
                line += f"{v:>22,.4g}"
        if r.get("note"):
            line += f"  ({r['note']})"
        out.append(line)
    if regressions:
        out.append("")
        out.append("REGRESSIONS (latest vs best comparable prior round):")
        for g in regressions:
            out.append(
                f"  {g['metric']:<32} {g['value']:>14,.6g} vs best "
                f"{g['best']:>14,.6g} ({g['best_round']}) "
                f"{g['change_frac']:+.1%} [{g['direction']}]")
    else:
        out.append("")
        out.append("no regressions against comparable prior rounds")
    if mode_regressions:
        out.append("")
        out.append("MODE REGRESSIONS (wave-pipeline downgrade vs prior "
                   "comparable round):")
        for g in mode_regressions:
            out.append(f"  {g['metric']:<32} {g['value']} vs "
                       f"{g['prior']} ({g['prior_round']})")
    if swap_blips:
        out.append("")
        out.append(f"SWAP BLIPS (hot-swap p99 > {_SWAP_BLIP_FLAG:g}x "
                   "steady p99 — the flip leaked into the request path):")
        for g in swap_blips:
            out.append(f"  {g['round']}: blip {g['value']:g}ms vs steady "
                       f"{g['steady']:g}ms ({g['ratio']:g}x)")
    if device_regressions:
        out.append("")
        out.append("DEVICE REGRESSIONS (latest multi-chip leg vs the "
                   "prior real one):")
        for g in device_regressions:
            out.append(f"  {g['metric']:<32} {g['value']} vs "
                       f"{g['prior']} ({g['prior_round']})")
    if measured_divergence:
        out.append("")
        out.append(f"MEASURED-VS-MODEL DIVERGENCE (> {_DIVERGENCE_FLAG:g}x "
                   "off the analytic roofline — the kernel or the cost "
                   "model is lying):")
        for g in measured_divergence:
            out.append(f"  {g['metric']:<40} frac "
                       f"{g['roofline_frac']:g} "
                       f"({g['divergence']:g}x {g['side']}) "
                       f"[{g['round']}]")
    trend = [t for t in canary_trend(rows)
             if "per_iter_s_change_frac" in t or "value_change_frac" in t]
    if trend:
        out.append("")
        out.append("canary trend (informational — degraded-backend rounds, "
                   "never a baseline):")
        for t in trend:
            bits = [f"  {t['round']} [{t['backend']}]"]
            if t.get("per_iter_s") is not None:
                bits.append(f"per_iter_s {t['per_iter_s']:g}")
                if "per_iter_s_change_frac" in t:
                    bits.append(f"({t['per_iter_s_change_frac']:+.1%})")
            if t.get("value") is not None:
                bits.append(f"value {t['value']:,.4g}")
                if "value_change_frac" in t:
                    bits.append(f"({t['value_change_frac']:+.1%})")
            out.append(" ".join(bits))
    return "\n".join(out)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Bench-round trajectory table + regression flags")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__)))],
                    help="BENCH_r*.json files, telemetry digests, or "
                         "directories (default: repo root)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable digest instead of the table")
    ap.add_argument("--threshold", type=float, default=0.1,
                    help="relative worsening that counts as a regression "
                         "(default 0.10)")
    ap.add_argument("--fail-on-regression", action="store_true",
                    help="exit 1 when any regression is flagged")
    args = ap.parse_args()
    rows = collect(args.paths)
    if not rows:
        print("no bench rounds found", file=sys.stderr)
        return 1
    regressions = find_regressions(rows, args.threshold)
    mode_regressions = find_mode_regressions(rows)
    swap_blips = find_swap_blips(rows)
    measured_divergence = find_measured_divergence(rows)
    device_regressions = find_device_regressions(rows)
    if args.json:
        print(json.dumps({"rounds": rows, "regressions": regressions,
                          "mode_regressions": mode_regressions,
                          "swap_blips": swap_blips,
                          "measured_divergence": measured_divergence,
                          "device_regressions": device_regressions,
                          "canary_trend": canary_trend(rows)}))
    else:
        print(render(rows, regressions, mode_regressions, swap_blips,
                     measured_divergence, device_regressions))
    if ((regressions or mode_regressions or swap_blips
         or measured_divergence or device_regressions)
            and args.fail_on_regression):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
