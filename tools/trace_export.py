"""Convert telemetry JSONL into Chrome trace-event / Perfetto JSON.

The trace plane (obs/spans.py) writes one ``span`` event per completed
span — serving requests (queue->coalesce->pad->execute) and training
iterations (iteration->phases) share the schema, so this tool renders
BOTH on one timeline: load the output at https://ui.perfetto.dev (or
``chrome://tracing``).

    python tools/trace_export.py /tmp/telem --out trace.json
    python tools/trace_export.py run.jsonl            # -> run.trace.json

Input is anything ``obs.report.load_events`` resolves (a telemetry dir,
a ``.jsonl`` file, or a glob).  Rows:

- every ``span`` event becomes one complete ("ph": "X") trace event;
  ``pid`` is the telemetry process index, ``tid`` a stable per-trace_id
  lane (named via thread_name metadata), so each request/iteration
  renders as its own track;
- when a stream has NO span events (tracing was off) but carries
  ``iteration`` records, per-iteration phase spans are synthesized from
  ``phase_s`` (stacked sequentially inside the iteration window) so a
  plain telemetry run still gets an approximate timeline — synthesized
  events are marked ``args.synthesized``;
- operational-plane events (online refreshes, drift checks, straggler
  breaches, the xprof plane's ``kernel_measured`` device-op summaries
  and ``compile`` walls) ride on their own ``ops/*`` tracks beside the
  spans (``_OPS_TRACKS``).

Timestamps are rebased to the earliest event so the timeline starts at
zero (Perfetto dislikes 50-year offsets).  Stdlib only.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _span_rows(events):
    return [e for e in events if e.get("event") == "span"
            and isinstance(e.get("t"), (int, float))]


def _synth_from_iterations(events):
    """Approximate span rows from ``iteration`` records: the iteration
    window is exact ([t - iter_s, t]); its phases stack sequentially in
    declaration order (their true overlap is not recorded)."""
    out = []
    for e in events:
        if e.get("event") != "iteration":
            continue
        t1 = e.get("t")
        dur_s = e.get("iter_s")
        if not isinstance(t1, (int, float)) or not dur_s:
            continue
        t0 = t1 - float(dur_s)
        trace = f"train-iter-{e.get('iteration')}"
        proc = e.get("_proc", 0)
        out.append({"event": "span", "t": t0,
                    "dur_ms": float(dur_s) * 1e3,
                    "name": "train/iteration", "trace_id": trace,
                    "span_id": f"it{e.get('iteration')}", "_proc": proc,
                    "attrs": {"iteration": e.get("iteration"),
                              "synthesized": True}})
        cursor = t0
        for phase, s in (e.get("phase_s") or {}).items():
            out.append({"event": "span", "t": cursor,
                        "dur_ms": float(s) * 1e3,
                        "name": f"phase/{phase}", "trace_id": trace,
                        "span_id": f"it{e.get('iteration')}/{phase}",
                        "parent_id": f"it{e.get('iteration')}",
                        "_proc": proc,
                        "attrs": {"synthesized": True}})
            cursor += float(s)
    return out


_OPS_TRACKS = {
    # telemetry event -> (track name, duration-field, scale to ms)
    "online_refresh": ("ops/online", "ms", 1.0),
    "refit": ("ops/online", "wall_s", 1e3),
    "drift_snapshot": ("ops/drift", None, 0.0),
    "quality_window": ("ops/drift", None, 0.0),
    # live-introspection plane (ISSUE 17): straggler breaches and the
    # measured-vs-model reconciliation cadence as instants on their own
    # tracks (a straggler event always carries breach=True, so it
    # renders as .../BREACH like a drift latch)
    "straggler": ("ops/straggler", None, 0.0),
    "reconciliation": ("ops/reconcile", None, 0.0),
    # measured-roofline plane (ISSUE 18, obs/xprof.py): the parsed
    # device-op summaries — one span per attributed kernel, duration =
    # its measured ms inside the capture window — and the compile plane
    # (backend-compile walls as spans, cache hits/misses + retraces as
    # instants) on their own tracks beside the host spans
    "kernel_measured": ("ops/xprof", "measured_ms", 1.0),
    "compile": ("ops/compile", "wall_s", 1e3),
}


def _synth_ops_tracks(events):
    """Span rows for the operational planes — online refreshes/refits
    as duration spans, drift snapshots and quality windows as instants —
    so the ops cadence renders on its own Perfetto track beside the
    request/iteration spans."""
    out = []
    for e in events:
        kind = e.get("event")
        spec = _OPS_TRACKS.get(kind)
        if spec is None or not isinstance(e.get("t"), (int, float)):
            continue
        trace, dur_field, scale = spec
        dur_ms = (float(e.get(dur_field, 0.0) or 0.0) * scale
                  if dur_field else 0.0)
        attrs = {k: v for k, v in e.items()
                 if k not in ("event", "t", "_proc")
                 and isinstance(v, (int, float, str, bool))}
        attrs["synthesized"] = True
        name = kind
        if kind == "kernel_measured" and e.get("kernel"):
            # the attributed scope IS the span name (lgbm/wave_hist,
            # unattributed, ...) so the xprof track reads like the
            # digest table; unknown scopes pass through verbatim
            name = str(e["kernel"])
        elif kind == "compile" and e.get("kind"):
            name = f"compile/{e['kind']}"
        if e.get("breach"):
            name += "/BREACH"
        out.append({"event": "span", "t": float(e["t"]) - dur_ms / 1e3,
                    "dur_ms": dur_ms, "name": name, "trace_id": trace,
                    "span_id": f"{kind}@{e['t']}",
                    "_proc": e.get("_proc", 0), "attrs": attrs})
    return out


def events_to_chrome(events) -> dict:
    """Merged telemetry events -> a Chrome trace-event document (dict).
    Round-trips: ``json.dump`` the result and Perfetto loads it."""
    spans = _span_rows(events)
    if not spans:
        spans = _synth_from_iterations(events)
    # the ops planes (online refresh/refit, drift/quality checks) ride
    # along whenever present — they have no true span events, so the
    # synthesized track is additive, not a fallback
    spans = spans + _synth_ops_tracks(events)
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    t_min = min(e["t"] for e in spans)
    tids = {}
    trace_events = []
    for e in spans:
        trace = str(e.get("trace_id") or "?")
        pid = int(e.get("_proc", 0) or 0)
        key = (pid, trace)
        if key not in tids:
            tids[key] = len(tids) + 1
            trace_events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tids[key], "args": {"name": trace}})
        args = {"trace_id": trace, "span_id": e.get("span_id")}
        if e.get("parent_id"):
            args["parent_id"] = e["parent_id"]
        args.update(e.get("attrs") or {})
        trace_events.append({
            "ph": "X", "name": str(e.get("name", "?")),
            "cat": str(e.get("name", "?")).split("/")[0],
            "ts": round((float(e["t"]) - t_min) * 1e6, 3),
            "dur": round(float(e.get("dur_ms", 0.0) or 0.0) * 1e3, 3),
            "pid": pid, "tid": tids[key], "args": args})
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"source": "lightgbm_tpu tools/trace_export.py",
                          "t_origin_unix_s": round(t_min, 6),
                          "spans": len(spans), "tracks": len(tids)}}


def export(path: str, out: str) -> dict:
    from lightgbm_tpu.obs.report import load_events
    doc = events_to_chrome(load_events(path))
    with open(out, "w") as fh:
        json.dump(doc, fh)
    return doc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Telemetry JSONL -> Chrome trace-event / Perfetto "
                    "JSON (serving request + training iteration spans on "
                    "one timeline)")
    ap.add_argument("path", help="telemetry dir, .jsonl file, or glob")
    ap.add_argument("--out", default="",
                    help="output file (default: <path>.trace.json)")
    args = ap.parse_args(argv)
    base = args.path.rstrip("/")
    if base.endswith(".jsonl"):
        base = base[:-len(".jsonl")]
    out = args.out or base + ".trace.json"
    doc = export(args.path, out)
    n = len(doc["traceEvents"])
    print(f"# wrote {out}: {n} trace event(s)"
          + ("" if n else " (no spans — was LGBM_TPU_TRACE on?)"))
    print("# open at https://ui.perfetto.dev or chrome://tracing")
    return 0 if n else 1


if __name__ == "__main__":
    sys.exit(main())
