"""Run the quick + slow test tiers and record per-tier evidence.

Every round needs "0 failures" to be a CHECKABLE claim, not a memory:
this tool runs each tier (the conftest.py quick/slow markers) as its own
pytest subprocess with the tier-1 hardening flags, times it, parses the
summary counts, and writes one ``SUITE_r{N}.json`` next to the
``BENCH_r*.json`` round artifacts (VERDICT round-5 next-round item #8).

    python tools/run_suite.py                      # quick + slow tiers
    python tools/run_suite.py --tiers quick        # tier-1 only
    python tools/run_suite.py --select tests/test_config.py --tiers quick

``--select`` narrows the collection target (a file or node id) — the
smoke path CI exercises.  Exit code: 0 when every tier passed (an empty
selection counts as passed and is noted), 1 otherwise.

The quick tier carries the differential-apply smoke
(``tests/test_wave_apply.py::test_batched_apply_differential_smoke``):
every quick run re-proves the batched one-pass wave split apply byte-
identical to the sequential oracle before any perf number is trusted.
It also carries the fused-kernel smoke (ISSUE 8,
``tests/test_hist_fused.py::test_fused_packed_smoke``): the packed
lane-pair + in-kernel-sibling wave kernel, run in Pallas interpret mode
on CPU, bit-matches the triple-layout unfused oracle — so a histogram-
pipeline regression can never hide behind a green perf round.  Since
ISSUE 11 it additionally carries the quantized + fused-grad smoke
(``tests/test_hist_quant.py::test_quant_fused_smoke``): int16
stochastic-rounded accumulation within its analytic error bound,
bit-identical across the packed/fused layout grid, and the fused
gradient pass bit-identical to its unfused oracle — the new modes
can't rot between TPU windows.  Since ISSUE 13 it also carries the
ranking-plane smoke (``tests/test_rank_device.py::
test_rank_wave_smoke_device_metric_parity``): a small lambdarank train
end-to-end through the wave path (``LGBM_TPU_FORCE_WAVE=interpret``)
with the device NDCG kernel asserted against the host oracle — CPU CI
exercises the whole ranking plane every quick run.

The ``serve`` tier is not a pytest marker: it runs
``tools/bench_serve.py --smoke`` — start the HTTP server in-process,
fire concurrent mixed-size requests, assert p99 recorded + the compile
count bounded by the pow2 bucket set + clean shutdown, and (ISSUE 6)
that ``/metrics`` and ``/debug/flight`` keep answering while the POST
storm runs and ``/health`` carries the load-balancer signals
(queue_rows, uptime_s, compile_count, slo_burn) — so every suite round
re-proves the serving engine AND its introspection plane end to end on
CPU.  Since ISSUE 9 the smoke pins ``--explain-frac 0.2``: a fifth of
the open-loop Poisson arrivals are ``/explain`` TreeSHAP requests, so
the explanation plane (its own microbatch queue + pow2 bucket family)
is re-proved by the same round — ``explain_served``,
``explain_no_failures`` and ``explain_buckets_bounded`` join the
check map.

The ``chaos`` tier (ISSUE 10) runs ``tools/chaos_serve.py --json``: the
serving chaos matrix — replica wedge (one wedged replica of a routed
pair costs capacity, never availability; breaker opens, half-open probe
recovers it), hot-swap under concurrent mixed /predict + /explain
traffic (zero request loss, no 5xx from the swap, every response
bit-consistent with its echoed model version), canary-gate rejection
(409, old version keeps serving), post-swap regression -> automatic
rollback + flight dump, and priority shedding (low shed first,
Retry-After on the 503, per-class counters in /metrics) — so every
suite round re-proves the whole serving resilience plane on CPU.

The ``faults`` tier (ISSUE 7) runs ``tools/fault_matrix.py --json``:
every ``LGBM_TPU_FAULTS`` injection point x recovery mode — transient
retry (bit-identical model), fatal abort (wedge checkpoint + flight
dump + bit-exact resume), CPU fallback, collective retry, stall
stamping, serve degrade-and-reprobe, checkpoint-write faults and
corrupt-checkpoint fallback — so every suite round re-proves the whole
fault-tolerance plane on CPU.  Since ISSUE 12 it also covers the
online loop: a refit fault leaves the old version serving, a crash
mid-train-continue resumes bit-exactly, and an ingest stall skips the
cadence with a logged + telemetry-stamped event.

The ``ingest`` tier (ISSUE 14) runs ``tools/ingest_bench.py --json``:
the streaming-ingestion smoke — a synthetic chunked stream two-pass
ingested with the bounded-memory proof (tracemalloc peak strictly
below the raw [N, F] f64 bytes the in-RAM path would materialize),
streamed-vs-``from_matrix`` bit identity on the same reservoir
sample, chunk-size invariance, and the distribution-shifted-tail
sampling regression — so every suite round re-proves that out-of-core
ingestion produces the exact same datasets the in-RAM loaders would.

The ``online`` tier (ISSUE 12) runs ``tools/online_smoke.py --json``:
the closed-loop end-to-end check — a drifting labeled stream drives
the OnlineLoop to >= 2 refreshed versions through
``POST /models/{name}/swap`` under concurrent zero-loss /predict
traffic, and a deliberately poisoned refit bounces off the canary
gate with the old version still serving.  Its JSON carries
``online_refresh_s`` / ``online_swap_ok``, trended by
``tools/bench_history.py`` from the ``ONLINE_r*.json`` artifact.

The ``drift`` tier (ISSUE 16) runs ``tools/drift_report.py --smoke
--json``: the model-quality monitoring plane — profile sidecar written
at ``save_model``, an i.i.d. replay scoring below ``tpu_drift_psi_warn``
(no false alarm), a seeded covariate-shift replay breaching and
latching within one cadence check, per-replica sketch merge bit-exact
vs the single-sketch oracle, and a label-flipped quality window
dropping windowed AUC past ``tpu_quality_drop_warn`` with the breach
annotated in the registry.  Its ``DRIFT_r*.json`` carries
``drift_psi_max`` / ``quality_auc_delta`` for ``bench_history``.

The ``board`` tier (ISSUE 17) runs ``tools/board_smoke.py --json``:
the live-training-introspection smoke — a short CPU train with the
train-side metrics exporter armed (``tpu_train_metrics_port=0``) while
a concurrent poller scrapes it: the Prometheus exposition parses
through the SAME reader the serving plane uses
(``serve.metrics.parse_prometheus``), ``/progress`` answers the full
JSON contract with a finite, converging ETA, ``/debug/flight`` serves
the live ring, and the train-thread seconds spent inside the board
hook stay under the 5% off-path overhead guard.

The ``arena`` tier (ISSUE 19) runs ``tools/arena_smoke.py --json``:
the zero-cold-start + multi-tenant plane — a warmed session exports
every pow2 bucket executable to the AOT store, a fresh session
deserializes and serves the full sweep with the compile counter pinned
at 0 and bit-identical output; binary-NaN / multiclass / categorical
tenants packed into one ``ForestArena`` predict bit-identically to
dedicated sessions; interleaved mixed-tenant submits coalesce into
shared device batches; and an impossible byte budget forces an LRU
eviction whose victim is transparently re-admitted on its next request.

The ``xprof`` tier (ISSUE 18) runs ``tools/xprof_smoke.py --json``:
the measured-roofline smoke — a tiny CPU train with the windowed
profiler capture armed (``LGBM_TPU_XPROF``) plus a cold persistent
compile cache: the trace parses with the stdlib-only reader, >= 3
distinct ``lgbm/*`` kernels attribute with nonzero measured ms and at
least one carries the analytic cost-model join, the emitted
``kernel_measured`` / ``compile`` events validate against their
schemas and render the digest's measured-roofline table, backend
compile walls + cache hit/miss + retrace gauges show on ``/metrics``,
and the disarmed per-iteration ``step()`` hook stays under the same
5% off-path overhead guard the board tier pins.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the tier-1 hardening flags (ROADMAP.md "Tier-1 verify"), minus the
# marker selection this tool owns per tier
_PYTEST_FLAGS = ["-q", "--continue-on-collection-errors",
                 "-p", "no:cacheprovider"]

_COUNT_RE = re.compile(
    r"(\d+)\s+(passed|failed|error(?:s)?|skipped|deselected|xfailed|"
    r"xpassed|warning(?:s)?)")


def parse_counts(output: str) -> dict:
    """Counts from pytest's final summary line (the last line that
    carries any '<n> passed/failed/...' tokens)."""
    counts = {}
    for line in reversed((output or "").splitlines()):
        found = _COUNT_RE.findall(line)
        if found:
            for n, kind in found:
                counts[kind.rstrip("s") if kind != "passed" else kind] = \
                    int(n)
            break
    return counts


def next_round(out_dir: str) -> int:
    n = 0
    for f in glob.glob(os.path.join(out_dir, "SUITE_r*.json")):
        m = re.search(r"SUITE_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


def run_tier(tier: str, select: str, timeout: int,
             runner=subprocess.run, py: str = sys.executable) -> dict:
    target = select or os.path.join(REPO, "tests")
    argv = [py, "-m", "pytest", target, "-m", tier] + _PYTEST_FLAGS
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.time()
    try:
        r = runner(argv, env=env, cwd=REPO, timeout=timeout,
                   capture_output=True, text=True)
        rc, out, err = r.returncode, r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired:
        rc, out, err = -1, "", f"timed out after {timeout}s"
    counts = parse_counts(out)
    # pytest exit 5 = nothing collected for this tier/selection — that is
    # evidence of an empty tier, not of a failure
    ok = rc == 0 or rc == 5
    return {
        "tier": tier,
        "cmd": " ".join(argv[2:]),
        "rc": rc,
        "ok": ok,
        "empty": rc == 5,
        "wall_s": round(time.time() - t0, 1),
        "counts": counts,
        "tail": (out + ("\n" + err if err else "")).splitlines()[-5:],
    }


# built-in (non-pytest) tiers: tier name -> argv tail under tools/
_TOOL_TIERS = {
    # --explain-frac pinned so the suite's serve leg always smokes the
    # explain plane (bench_serve adds explain_served /
    # explain_buckets_bounded checks when the mixed leg runs), even if
    # the environment zeroes SERVE_EXPLAIN_FRAC
    "serve": ["bench_serve.py", "--smoke", "--explain-frac", "0.2"],
    "faults": ["fault_matrix.py", "--json"],
    # serving chaos matrix (ISSUE 10): replica wedge, swap-mid-flight,
    # canary rejection, post-swap rollback, priority shedding — every
    # fleet failure mode re-proved on CPU each suite round
    "chaos": ["chaos_serve.py", "--json"],
    # online loop end-to-end (ISSUE 12): ingest -> refit -> canary-gated
    # swap under live traffic, poisoned refit rejected — the closed loop
    # re-proved on CPU each suite round
    "online": ["online_smoke.py", "--json"],
    # streaming ingestion (ISSUE 14): the synthetic-stream bench's
    # verdict map — bounded-memory proof (peak << raw [N,F] bytes),
    # streamed-vs-in-RAM bit identity, chunk-size invariance, and the
    # shifted-tail sampling regression — re-proved on CPU each round;
    # its INGEST_rN.json carries ingest_rows_per_s for bench_history
    "ingest": ["ingest_bench.py", "--json"],
    # drift/quality plane (ISSUE 16): profile sidecar written at save,
    # i.i.d. replay quiet, seeded covariate shift breaches + latches,
    # sketch merge bit-exact, label-flip quality breach annotated in the
    # registry — the monitoring plane re-proved on CPU each round; its
    # DRIFT_rN.json carries drift_psi_max / quality_auc_delta for
    # bench_history
    "drift": ["drift_report.py", "--smoke", "--json"],
    # live training introspection (ISSUE 17): exporter-armed CPU train
    # scraped concurrently — Prometheus exposition parses through the
    # shared serve reader, /progress carries a finite converging ETA,
    # the flight endpoint answers, and the board hook stays inside the
    # 5% off-path overhead guard
    "board": ["board_smoke.py", "--json"],
    # measured-roofline plane (ISSUE 18): windowed profiler capture on a
    # tiny CPU train -> stdlib trace parse -> >=3 lgbm/* kernels
    # attributed with a cost-model join, kernel_measured/compile events
    # validating, compile walls + cache hit/miss on the board, and the
    # disarmed step() hook inside the same 5% off-path overhead guard
    "xprof": ["xprof_smoke.py", "--json"],
    # zero-cold-start plane (ISSUE 19): AOT export -> deserialize ->
    # serve round-trip with the compile counter pinned at 0 and
    # bit-identical output, multi-tenant arena parity across the
    # binning surface (NaN / multiclass / categorical), cross-model
    # coalescing, and byte-budget eviction with transparent
    # re-admission — re-proved on CPU each suite round
    "arena": ["arena_smoke.py", "--json"],
    # elastic multi-host fleet (ISSUE 20): 3-process gang launches over
    # the host-TCP transport — plain/bagging/ranking bit-exact vs the
    # single-process oracle, quiet healthy-path event trail, and the
    # kill-one-rank detect/rollback/heal recovery completing bit-exact;
    # its FLEET_rN.json carries fleet_ranks / fleet_recoveries for
    # bench_history
    "fleet": ["fleet_smoke.py", "--json"],
}


def run_tool_smoke(tier: str, timeout: int, runner=subprocess.run,
                   py: str = sys.executable) -> dict:
    """A built-in tool tier (serve smoke / fault matrix): one subprocess
    whose last JSON line carries a per-check verdict map — that map
    becomes the tier's counts."""
    tool = _TOOL_TIERS[tier]
    argv = [py, os.path.join(REPO, "tools", tool[0])] + tool[1:]
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = env.get("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    t0 = time.time()
    try:
        r = runner(argv, env=env, cwd=REPO, timeout=timeout,
                   capture_output=True, text=True)
        rc, out, err = r.returncode, r.stdout or "", r.stderr or ""
    except subprocess.TimeoutExpired:
        rc, out, err = -1, "", f"timed out after {timeout}s"
    parsed = None
    for line in reversed(out.splitlines()):
        if line.strip().startswith("{"):
            try:
                parsed = json.loads(line)
                break
            except ValueError:
                continue
    checks = (parsed or {}).get("checks") or {}
    counts = {"passed": sum(1 for v in checks.values() if v),
              "failed": sum(1 for v in checks.values() if not v)}
    return {
        "tier": tier,
        "cmd": "tools/" + " ".join(tool),
        "rc": rc,
        "ok": rc == 0 and bool((parsed or {}).get("ok")),
        "empty": False,
        "wall_s": round(time.time() - t0, 1),
        "counts": counts,
        "checks": checks,
        "tail": (out + ("\n" + err if err else "")).splitlines()[-5:],
    }


def run_serve_smoke(timeout: int, runner=subprocess.run,
                    py: str = sys.executable) -> dict:
    """Back-compat alias for the serve tool tier."""
    return run_tool_smoke("serve", timeout, runner=runner, py=py)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Run the quick/slow test tiers and write SUITE_rN.json")
    ap.add_argument("--tiers", default="quick,slow,serve,faults,chaos,"
                                       "online,ingest,drift,board,xprof,"
                                       "arena,fleet",
                    help="comma list of tiers: pytest markers plus the "
                         "built-in 'serve' smoke, 'faults' matrix, "
                         "'chaos' serving-chaos, 'online' closed-loop, "
                         "'ingest' streaming-ingestion, 'drift' "
                         "monitoring, 'board' train-introspection, "
                         "'xprof' measured-roofline, 'arena' "
                         "zero-cold-start and 'fleet' elastic-fleet "
                         "legs (default quick,slow,serve,faults,chaos,"
                         "online,ingest,drift,board,xprof,arena,fleet)")
    ap.add_argument("--select", default="",
                    help="pytest collection target (file or node id) "
                         "instead of the whole tests/ dir")
    ap.add_argument("--timeout", type=int, default=3600,
                    help="per-tier subprocess timeout (default 3600)")
    ap.add_argument("--out", default=REPO,
                    help="artifact directory (default: repo root)")
    ap.add_argument("--round", type=int, default=0,
                    help="round number (default: next free SUITE_rN)")
    ap.add_argument("--no-write", action="store_true",
                    help="print the record without writing SUITE_rN.json")
    args = ap.parse_args(argv)

    tiers = [t.strip() for t in args.tiers.split(",") if t.strip()]
    if args.select and len(tiers) > 1:
        # --select narrows pytest collection; the tool tiers are not
        # pytest tiers, so a narrowed run drops them — unless a tool
        # tier is the ONLY tier asked for (then it runs, ignoring the
        # selection)
        tiers = [t for t in tiers if t not in _TOOL_TIERS]
    record = {"kind": "suite", "t": round(time.time(), 1), "tiers": {}}
    total = 0.0
    for tier in tiers:
        if tier in _TOOL_TIERS:
            print(f"# tier {tier}: tools/"
                  f"{' '.join(_TOOL_TIERS[tier])} ...", flush=True)
            res = run_tool_smoke(tier, args.timeout)
            record["tiers"][tier] = res
            total += res["wall_s"]
            print(f"# tier {tier}: rc={res['rc']} {res['counts']} "
                  f"({res['wall_s']}s)", flush=True)
            continue
        print(f"# tier {tier}: pytest -m {tier} "
              f"{args.select or 'tests/'} ...", flush=True)
        res = run_tier(tier, args.select, args.timeout)
        record["tiers"][tier] = res
        total += res["wall_s"]
        print(f"# tier {tier}: rc={res['rc']} {res['counts']} "
              f"({res['wall_s']}s)", flush=True)
    record["wall_s"] = round(total, 1)
    record["ok"] = all(t["ok"] for t in record["tiers"].values())
    record["failed"] = sum(t["counts"].get("failed", 0)
                           + t["counts"].get("error", 0)
                           for t in record["tiers"].values())
    n = args.round or next_round(args.out)
    record["n"] = n
    if not args.no_write:
        path = os.path.join(args.out, f"SUITE_r{n:02d}.json")
        with open(path, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# wrote {path}")
    print(json.dumps({k: record[k] for k in
                      ("n", "ok", "failed", "wall_s")}))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
