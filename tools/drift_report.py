"""Profile-vs-live drift report + the CPU drift smoke (ISSUE 16).

Report mode reads a model's ``.quality.json`` sidecar (obs/drift.py)
and prints the reference profile; given ``--stream`` (a JSONL file of
``{"x": [...]}`` rows — the online-loop stream format, labels ignored)
it replays the rows through a ``DriftSketch`` and prints the
profile-vs-sketch table: per-feature PSI/KS, the prediction-histogram
scores, and the breach list vs ``tpu_drift_psi_warn``.

    python tools/drift_report.py model.txt
    python tools/drift_report.py model.txt --stream live.jsonl

``--smoke`` is the self-contained end-to-end check the ``drift`` suite
tier runs (tools/run_suite.py): train a small binary model (profile
sidecar written at save), serve it through an in-process
``ModelRegistry``, and prove the plane on CPU:

- **clean traffic stays quiet**: an i.i.d. replay scores PSI below the
  warn threshold — no breach, no false alarm;
- **shifted traffic is flagged**: a seeded covariate-shift replay
  (scaled + offset marginals) drives PSI past ``tpu_drift_psi_warn``
  within one forced cadence check and latches the breach;
- **merge = oracle**: two sketches fed disjoint halves of the replay
  merge bit-exactly to the single-sketch counts (the ServeMetrics
  contract);
- **quality windows close the loop**: a label-flipped window drops
  windowed AUC past ``tpu_quality_drop_warn`` and the breach lands in
  the registry's ``models()`` annotation.

The ``DRIFT_rN.json`` artifact carries ``drift_psi_max`` (shifted
replay) and ``quality_auc_delta`` — ``tools/bench_history.py`` trends
both and flags breach rounds like canaries.

    python tools/drift_report.py --smoke --json
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

CHECKS = {}


def check(name, ok, detail=""):
    CHECKS[name] = bool(ok)
    print(f"# {'ok ' if ok else 'FAIL'} {name}"
          + (f" — {detail}" if detail and not ok else ""), flush=True)


def _next_round(out_dir):
    n = 0
    for f in glob.glob(os.path.join(out_dir, "DRIFT_r*.json")):
        m = re.search(r"DRIFT_r(\d+)\.json$", os.path.basename(f))
        if m:
            n = max(n, int(m.group(1)))
    return n + 1


# ---------------------------------------------------------------------------
# report mode
# ---------------------------------------------------------------------------

def _load_stream_rows(path):
    """Rows from a JSONL stream ({"x": [...]}; "features" accepted),
    malformed lines skipped like online/loop.py's reader."""
    rows, bad = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                rows.append([float(v) for v in
                             rec.get("x", rec.get("features"))])
            except (ValueError, TypeError, AttributeError):
                bad += 1
    if bad:
        print(f"# skipped {bad} malformed stream line(s)")
    return np.asarray(rows, np.float64) if rows else np.zeros((0, 0))


def report(model_path: str, stream: str, psi_warn: float) -> int:
    from lightgbm_tpu.obs.drift import (DriftSketch, QualityProfile,
                                        coarsen, ks, profile_path, psi)
    side = profile_path(model_path)
    if not os.path.isfile(side):
        print(f"# no profile sidecar at {side} — retrain with "
              f"tpu_quality_profile=true and save_model()")
        return 1
    prof = QualityProfile.load(side)
    meta = prof.meta
    print(f"# profile {side}")
    print(f"#   reference rows {meta.get('rows')}, "
          f"{meta.get('num_features')} feature(s), "
          f"train_auc {meta.get('train_auc')}")
    numeric = prof.numeric_records()
    if not stream:
        print(f"#   {len(numeric)} numerical feature record(s), "
              f"{len(prof.features) - len(numeric)} categorical "
              f"(excluded from drift)")
        for rec in numeric:
            c = np.asarray(rec["counts"], np.float64)
            top = int(np.argmax(c)) if c.size else -1
            print(f"    {rec['name']:<24} bins={rec['num_bin']:<4} "
                  f"mode_bin={top} nan_bin={rec['nan_bin']}")
        return 0
    X = _load_stream_rows(stream)
    if not X.size:
        print("# stream is empty — nothing to score")
        return 1
    sk = DriftSketch(prof)
    sk.observe_features(X)
    snap = sk.snapshot()
    print(f"# live stream {stream}: {snap['feat_rows']} row(s)")
    print(f"  {'feature':<24}{'psi':>10}{'ks':>10}  verdict")
    breaches = []
    for rec, live in zip(sk.records, snap["feat_counts"]):
        rc, lc = coarsen(rec["counts"], live)
        p, k = psi(rc, lc), ks(rc, lc)
        verdict = ("BREACH" if p > psi_warn
                   else "shift" if p > 0.1 else "ok")
        if p > psi_warn:
            breaches.append(rec["name"])
        print(f"  {rec['name']:<24}{p:>10.4f}{k:>10.4f}  {verdict}")
    if breaches:
        print(f"# {len(breaches)} feature(s) past psi_warn={psi_warn}: "
              + ", ".join(breaches))
    else:
        print(f"# no feature past psi_warn={psi_warn}")
    return 0


# ---------------------------------------------------------------------------
# smoke mode
# ---------------------------------------------------------------------------

def smoke(args) -> int:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.obs.drift import (DriftMonitor, DriftSketch,
                                        QualityProfile, profile_path)
    from lightgbm_tpu.serve import ModelRegistry
    from lightgbm_tpu.serve.quality import QualityTracker

    t0 = time.time()
    art = tempfile.mkdtemp(prefix="drift_smoke_")
    rng = np.random.default_rng(16)
    # every cadence knob pinned: the smoke must not depend on ambient
    # env; flight dumps land in the artifact dir, not the repo root
    os.environ["LGBM_TPU_DRIFT_SAMPLE_RATE"] = "1.0"
    os.environ["LGBM_TPU_DRIFT_MIN_ROWS"] = "64"
    os.environ["LGBM_TPU_FLIGHT_DIR"] = art

    P = {"objective": "binary", "num_leaves": 7, "min_data_in_leaf": 5,
         "verbose": -1, "tpu_serve_replicas": 1,
         "tpu_serve_max_batch": 256, "tpu_serve_rollback_watch_s": 0.0,
         "tpu_quality_window": 256, "tpu_quality_drop_warn": 0.05}
    cfg = Config.from_params(P)

    Xt = rng.normal(size=(1200, 6))
    yt = (Xt[:, 0] + 0.6 * Xt[:, 1] - 0.3 * Xt[:, 2]
          > 0).astype(np.float64)
    ds = lgb.Dataset(Xt, label=yt, params=P)
    bst = lgb.train(P, ds, num_boost_round=8, verbose_eval=False)
    model_path = os.path.join(art, "model.txt")
    bst.save_model(model_path)
    side = profile_path(model_path)
    check("profile_sidecar_written", os.path.isfile(side), side)
    prof = QualityProfile.load(side)
    check("profile_has_reference",
          prof.meta.get("rows") == 1200 and len(prof.numeric_records()) == 6
          and prof.meta.get("train_auc") is not None, prof.meta)

    reg = ModelRegistry(config=cfg)
    reg.add_model("default", model_path)
    router = reg.resolve(None).router
    mon = getattr(router, "drift", None)
    check("monitor_armed", mon is not None)
    if mon is None:
        print(json.dumps({"kind": "drift", "ok": False, "checks": CHECKS}))
        return 1

    # ---- clean traffic stays quiet ---------------------------------
    for _ in range(4):
        router.predict(rng.normal(size=(128, 6)))
    iid = mon.maybe_check(force=True)
    check("clean_traffic_quiet",
          iid is not None and iid["psi_max"] <= mon.psi_warn
          and not mon.breach,
          iid and {k: iid[k] for k in ("psi_max", "pred_psi")})
    psi_iid = iid["psi_max"] if iid else None

    # ---- seeded covariate shift is flagged -------------------------
    for _ in range(4):
        Xs = rng.normal(size=(128, 6)) * 2.5 + 1.5
        router.predict(Xs)
    shifted = mon.maybe_check(force=True)
    check("shifted_traffic_flagged",
          shifted is not None and shifted["psi_max"] > mon.psi_warn,
          shifted and {k: shifted[k] for k in ("psi_max", "pred_psi")})
    check("breach_latched", mon.breach is not None
          and "feature_psi" in (mon.breach or {}).get("kinds", ()),
          mon.breach)
    psi_shifted = shifted["psi_max"] if shifted else None

    # ---- merge across replicas == single-sketch oracle -------------
    Xm = rng.normal(size=(512, 6)) * 1.7 - 0.4
    oracle, a, b = (DriftSketch(prof), DriftSketch(prof),
                    DriftSketch(prof))
    oracle.observe_features(Xm)
    oracle.observe_preds(np.arange(512, dtype=np.float64) / 512)
    a.observe_features(Xm[:200])
    a.observe_preds(np.arange(200, dtype=np.float64) / 512)
    b.observe_features(Xm[200:])
    b.observe_preds(np.arange(200, 512, dtype=np.float64) / 512)
    a.merge(b)
    sa, so = a.snapshot(), oracle.snapshot()
    merged_exact = (
        sa["feat_rows"] == so["feat_rows"]
        and sa["pred_rows"] == so["pred_rows"]
        and all(np.array_equal(x, y) for x, y in
                zip(sa["feat_counts"], so["feat_counts"]))
        and np.array_equal(sa["pred_counts"], so["pred_counts"]))
    check("sketch_merge_bit_exact", merged_exact)

    # ---- quality window: label flip -> breach -> registry ----------
    tracker = QualityTracker(
        lambda X: router.predict(X, raw_score=True), prof, config=cfg,
        registry=reg, model_name="default")
    Xq = rng.normal(size=(256, 6))
    yq = (Xq[:, 0] + 0.6 * Xq[:, 1] - 0.3 * Xq[:, 2] > 0)
    tracker.add(Xq, 1.0 - yq.astype(np.float64))   # flipped labels
    check("quality_breach_detected", tracker.breaches >= 1,
          tracker.stats())
    listing = {m["name"]: m for m in reg.models()}
    qb = listing.get("default", {}).get("quality_breach")
    check("registry_annotated", qb is not None
          and qb.get("auc_delta") is not None, qb)
    auc_delta = (qb or {}).get("auc_delta")
    dumps = glob.glob(os.path.join(art, "FLIGHT_r*.json"))
    check("breach_flight_dump", len(dumps) >= 1, art)

    record = {
        "kind": "drift",
        "t": round(time.time(), 1),
        "wall_s": round(time.time() - t0, 1),
        "backend": "cpu",
        "checks": CHECKS,
        "ok": all(CHECKS.values()),
        "drift_psi_max": psi_shifted,
        "drift_psi_iid": psi_iid,
        "quality_auc_delta": auc_delta,
        "drift_breaches": mon.breach_count,
        "artifacts_dir": art,
    }
    if not args.no_write:
        n = _next_round(args.out)
        path = os.path.join(args.out, f"DRIFT_r{n:02d}.json")
        with open(path, "w") as fh:
            json.dump(record, fh, indent=1)
        print(f"# wrote {path}")
    if args.json:
        print(json.dumps(record))
    else:
        print(f"# {sum(CHECKS.values())}/{len(CHECKS)} checks passed "
              f"({record['wall_s']}s)")
    return 0 if record["ok"] else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Quality-profile drift report / CPU drift smoke")
    ap.add_argument("model", nargs="?", default="",
                    help="model file (its .quality.json sidecar is read)")
    ap.add_argument("--stream", default="",
                    help='JSONL file of {"x": [...]} rows to score '
                         "against the profile")
    ap.add_argument("--psi-warn", type=float, default=0.25,
                    help="breach threshold for the report table "
                         "(default 0.25)")
    ap.add_argument("--smoke", action="store_true",
                    help="run the self-contained end-to-end drift smoke")
    ap.add_argument("--json", action="store_true",
                    help="(smoke) print a machine-readable verdict line")
    ap.add_argument("--out", default=REPO,
                    help="DRIFT_rN.json artifact dir (default: repo root)")
    ap.add_argument("--no-write", action="store_true",
                    help="(smoke) skip writing the DRIFT_rN.json artifact")
    args = ap.parse_args(argv)
    if args.smoke:
        return smoke(args)
    if not args.model:
        ap.error("model path required (or --smoke)")
    return report(args.model, args.stream, args.psi_warn)


if __name__ == "__main__":
    sys.exit(main())
