"""Train-board exporter smoke — the ``board`` suite tier (ISSUE 17).

Runs a short CPU train with the train-side metrics exporter armed
(``tpu_train_metrics_port=0`` → ephemeral port) while a poller thread
scrapes ``GET /metrics`` and ``GET /progress`` concurrently, then
proves the introspection plane end to end:

- **board_started / board_stopped**: the engine arms the exporter and
  tears it down with the run;
- **prometheus_parses**: the text exposition parses through the SAME
  reader the serving plane uses (``serve.metrics.parse_prometheus``)
  and carries the train series (iteration, eta, row_iters_per_s);
- **progress_fields**: /progress answers with the full JSON contract
  (iteration/total_rounds/eta_s/recent/checkpoint/...);
- **iteration_advances**: successive scrapes see the iteration move;
- **eta_converging**: every sampled ETA is finite and the estimate
  shrinks as the run completes (smoothed, so monotone within slack);
- **flight_endpoint**: /debug/flight serves the live ring;
- **overhead_ok**: train-thread seconds spent inside the board hook
  stay under 5% of train wall — the same off-path guard
  tests/test_obs.py pins for the telemetry sink.

    python tools/board_smoke.py --json

Last stdout line is the ``{"ok": ..., "checks": ...}`` verdict map
(the tools/run_suite.py tool-tier contract).  Exit 0 iff all pass.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the env override beats the config knob — pin it so an outer
# LGBM_TPU_TRAIN_METRICS=off can't turn the smoke into a no-op
os.environ["LGBM_TPU_TRAIN_METRICS"] = "0"

ROUNDS = 20
POLL_S = 0.02
PROGRESS_KEYS = ("iteration", "total_rounds", "start_round", "eta_s",
                 "ema_iter_s", "row_iters_per_s", "recent", "checkpoint",
                 "uptime_s")


def _fetch(url: str, timeout: float = 3.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def run_smoke() -> dict:
    import numpy as np

    import lightgbm_tpu as lgb
    from lightgbm_tpu.obs import board
    from lightgbm_tpu.serve.metrics import parse_prometheus

    rng = np.random.default_rng(7)
    X = rng.normal(size=(4000, 12))
    y = (X[:, 0] + 0.4 * X[:, 1] - 0.2 * X[:, 2] > 0).astype(np.float64)
    params = {"objective": "binary", "num_leaves": 15,
              "min_data_in_leaf": 5, "verbose": -1,
              "tpu_train_metrics_port": 0}
    ds = lgb.Dataset(X, label=y, params=params)

    samples = []          # (t, iteration, eta_s) per successful scrape
    state = {"board": None, "metrics": None, "progress": None,
             "flight": None, "errors": 0, "stop": False}

    def poll():
        while not state["stop"]:
            b = board.current()
            if b is None or not b.port:
                time.sleep(POLL_S)
                continue
            state["board"] = b
            try:
                mtext = _fetch(b.url + "/metrics").decode()
                pr = json.loads(_fetch(b.url + "/progress"))
                state["metrics"] = mtext
                state["progress"] = pr
                if state["flight"] is None:
                    state["flight"] = json.loads(
                        _fetch(b.url + "/debug/flight"))
                if pr.get("iteration") is not None:
                    samples.append((time.time(), int(pr["iteration"]),
                                    pr.get("eta_s")))
            except Exception:
                state["errors"] += 1
            time.sleep(POLL_S)

    poller = threading.Thread(target=poll, daemon=True)
    poller.start()
    t0 = time.perf_counter()
    lgb.train(params, ds, num_boost_round=ROUNDS)
    wall = time.perf_counter() - t0
    state["stop"] = True
    poller.join(timeout=5)

    checks = {}
    checks["board_started"] = state["metrics"] is not None
    checks["board_stopped"] = not board.active()

    parsed = {}
    if state["metrics"]:
        try:
            parsed = parse_prometheus(state["metrics"])
        except Exception:
            parsed = {}
    checks["prometheus_parses"] = all(
        k in parsed for k in ("tpu_train_iteration",
                              "tpu_train_eta_seconds",
                              "tpu_train_row_iters_per_s",
                              "tpu_train_total_rounds"))

    pr = state["progress"] or {}
    checks["progress_fields"] = all(k in pr for k in PROGRESS_KEYS)

    iters = [s[1] for s in samples]
    checks["iteration_advances"] = bool(iters) and max(iters) > min(iters)

    etas = [s[2] for s in samples if s[2] is not None]
    finite = bool(etas) and all(
        isinstance(e, (int, float)) and math.isfinite(e) and e >= 0
        for e in etas)
    # smoothed estimate: require net convergence (last well below the
    # peak), not strict per-sample monotonicity — the EMA wobbles
    checks["eta_converging"] = (finite
                                and etas[-1] <= max(etas) + 1e-9
                                and etas[-1] < 0.5 * max(etas) + 1e-9)

    fl = state["flight"] or {}
    checks["flight_endpoint"] = bool(fl.get("enabled")) and \
        isinstance(fl.get("events"), list)

    b = state["board"]
    hook_s = float(getattr(b, "hook_s", 0.0)) if b is not None else -1.0
    checks["overhead_ok"] = b is not None and hook_s < 0.05 * wall

    return {
        "kind": "board",
        "t": round(time.time(), 1),
        "rounds": ROUNDS,
        "wall_s": round(wall, 3),
        "hook_s": round(hook_s, 6),
        "scrapes": len(samples),
        "scrape_errors": state["errors"],
        "port": getattr(b, "port", None) if b is not None else None,
        "eta_first": etas[0] if etas else None,
        "eta_last": etas[-1] if etas else None,
        "checks": checks,
        "ok": all(checks.values()),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Exporter-armed CPU train smoke (board suite tier)")
    ap.add_argument("--json", action="store_true",
                    help="print only the JSON verdict line")
    args = ap.parse_args(argv)
    record = run_smoke()
    if not args.json:
        for k, v in record["checks"].items():
            print(f"  {'PASS' if v else 'FAIL'}  {k}")
    print(json.dumps(record))
    return 0 if record["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
