"""Deprecated shim: the telemetry summarizer now lives at
``python -m lightgbm_tpu.obs.report <path> [--json]`` (the CLI moved
into the library so the report, its renderer, and its schemas version
together).  This wrapper keeps existing invocations working:

    python tools/telemetry_report.py <path> [--json]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# note: import the submodule explicitly — lightgbm_tpu.obs exports a
# report() FUNCTION (the timetag phase report) under the same name
from lightgbm_tpu.obs.report import main  # noqa: E402

if __name__ == "__main__":
    print("note: tools/telemetry_report.py is a shim; use "
          "`python -m lightgbm_tpu.obs.report` directly", file=sys.stderr)
    sys.exit(main())
