"""Merge LGBM_TPU_TELEMETRY JSONL files into a per-phase / per-iteration
summary.

Usage:
    python tools/telemetry_report.py <path> [--json]

``<path>`` is the telemetry directory (merges every
``telemetry.{process_index}.jsonl`` in it), a single ``.jsonl`` file, or
a glob.  Default output is a human-readable table; ``--json`` prints the
machine-readable digest (the same shape bench.py embeds as its
``telemetry`` field).
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# note: import the submodule explicitly — lightgbm_tpu.obs exports a
# report() FUNCTION (the timetag phase report) under the same name
from lightgbm_tpu.obs.report import (load_events, render,  # noqa: E402
                                     summarize, telemetry_files)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Summarize lightgbm_tpu telemetry JSONL files")
    ap.add_argument("path", help="telemetry dir, one .jsonl file, or a glob")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable digest instead of "
                         "the table")
    args = ap.parse_args()

    files = telemetry_files(args.path)
    if not files:
        print(f"no telemetry files under {args.path!r}", file=sys.stderr)
        return 1
    digest = summarize(load_events(args.path))
    if args.json:
        print(json.dumps(digest))
    else:
        print(f"merged {len(files)} file(s)")
        print(render(digest))
    return 0


if __name__ == "__main__":
    sys.exit(main())
